"""Cluster controller — the control plane of the runtime.

Parity target: the reference GCS server (src/ray/gcs/gcs_server/gcs_server.h:90
and its per-domain managers: GcsNodeManager, GcsActorManager
(gcs_actor_manager.cc:1410 max_restarts), GcsPlacementGroupManager,
GcsJobManager, internal KV (gcs_kv_manager.h), GcsHealthCheckManager
(gcs_health_check_manager.h:45)) PLUS the GCS-side ClusterTaskManager: unlike
the reference — which scheduls most tasks on per-node raylets with spillback —
this controller makes all placement decisions centrally. TPU-era rationale:
slices are long-lived gang-scheduled resources; central decisions avoid the
raylet spillback dance (normal_task_submitter.cc:461) entirely.

Also plays the object directory role (reference
ownership_object_directory.h): oid -> holder addresses, with inline storage
for small objects (reference CoreWorkerMemoryStore memory_store.h:45).
"""

from __future__ import annotations

import asyncio
import bisect
import logging
import os
import time
from collections import deque
from typing import Optional

from ray_tpu._private import rpc
from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.scheduler import NodeState, pick_node
from ray_tpu._private.task_spec import ACTOR_CREATE, TaskSpec

logger = logging.getLogger(__name__)


class _ObjectEntry:
    __slots__ = ("state", "inline", "holders", "size", "waiters", "owner",
                 "error", "escaped", "borrowers", "dying_at", "plane",
                 "device_worker", "device_node")

    def __init__(self):
        self.state = "pending"  # pending | ready | lost
        self.inline = None  # list[bytes] | None
        self.holders: set[tuple] = set()
        self.size = 0
        self.waiters: list[asyncio.Future] = []
        self.owner: Optional[str] = None
        self.error = None  # serialized error blob (parts) shared with owner
        # Device object plane (README "Device objects"): "device" entries
        # hold only a placeholder inline; the payload is pinned in the
        # producing worker's DeviceObjectTable. device_worker/device_node
        # drive the free fan-out and the producer-death lost sweep.
        self.plane: Optional[str] = None  # None/"host" | "device"
        self.device_worker: Optional[str] = None
        self.device_node: Optional[str] = None
        # Borrower protocol (reference reference_count.h:72): an oid that
        # ESCAPED its owner (was serialized into a payload another process
        # can see) is not freed when the owner's refcount hits zero — it is
        # marked dying and survives while registered borrowers exist, plus a
        # grace TTL covering the in-flight window between the owner shipping
        # the ref and the borrower registering.
        self.escaped = False
        self.borrowers: set[str] = set()  # worker ids holding borrowed refs
        self.dying_at: Optional[float] = None  # owner freed; sweep after TTL

    def wake(self):
        for fut in self.waiters:
            if not fut.done():
                fut.set_result(None)
        self.waiters.clear()


class _ActorEntry:
    __slots__ = (
        "spec", "state", "node_id", "worker_id", "address", "instance",
        "restarts_used", "name", "namespace", "death_cause", "waiters",
        "resources_held",
    )

    def __init__(self, spec: TaskSpec):
        self.spec = spec
        self.state = "PENDING"  # PENDING | ALIVE | RESTARTING | DEAD
        self.node_id = None
        self.worker_id = None
        self.address = None  # (host, port) of hosting worker's RPC server
        self.instance = 0  # bumped every restart so stale handles re-resolve
        self.restarts_used = 0
        self.name = spec.actor_name
        self.namespace = spec.namespace
        self.death_cause = None
        self.waiters: list[asyncio.Future] = []
        self.resources_held = False  # True while a node's resources back this actor

    def wake(self):
        for fut in self.waiters:
            if not fut.done():
                fut.set_result(None)
        self.waiters.clear()


#: Decimation factor for the telemetry ring: every DECIM raw points aging
#: out of the recent tier fold into ONE averaged history point.
_TELEM_DECIM = 8

#: Controller self-telemetry: per-RPC-method latency bucket boundaries
#: (seconds). Matches rt_rpc_frame_seconds' spirit but tuned to handler
#: execution times; shared by every method's histogram.
_RPC_BOUNDS = [0.0002, 0.001, 0.005, 0.02, 0.1, 0.5, 2.0]


class _SeriesRing:
    """Bounded two-tier timeseries for one (node, series[, worker]): a raw
    recent deque plus a decimated history deque (mean of every
    _TELEM_DECIM points aging out of raw). Memory is O(2 * points) per
    series regardless of runtime; timestamps stay monotone because append
    rejects out-of-order points."""

    __slots__ = ("raw", "hist", "acc_sum", "acc_n", "last_ts")

    def __init__(self, points: int):
        self.raw: deque = deque()
        self.hist: deque = deque(maxlen=points)
        self.acc_sum = 0.0
        self.acc_n = 0
        self.last_ts = 0.0

    def append(self, ts: float, val: float, points: int) -> None:
        if ts <= self.last_ts:
            return  # late/duplicate batch: keep the series monotone
        while len(self.raw) >= max(2, points):
            old_ts, old_val = self.raw.popleft()
            self.acc_sum += old_val
            self.acc_n += 1
            if self.acc_n >= _TELEM_DECIM:
                self.hist.append((old_ts, self.acc_sum / self.acc_n))
                self.acc_sum = 0.0
                self.acc_n = 0
        self.raw.append((ts, float(val)))
        self.last_ts = ts

    def points(self, since: float | None = None) -> list:
        out = [list(p) for p in self.hist] + [list(p) for p in self.raw]
        if since is not None:
            out = [p for p in out if p[0] > since]
        return out

    def latest(self) -> tuple | None:
        if self.raw:
            return self.raw[-1]
        if self.hist:
            return self.hist[-1]
        return None


class Controller:
    def __init__(self, session_id: str):
        self.session_id = session_id
        self.server = rpc.RpcServer(self._on_request, self._on_push, self._on_conn_close)
        self.nodes: dict[str, NodeState] = {}
        self.node_conns: dict[str, rpc.Connection] = {}
        self.client_conns: dict[str, rpc.Connection] = {}  # worker_id -> conn
        self.objects: dict[str, _ObjectEntry] = {}
        # Device-plane directory index: producer worker id -> ready device
        # oids. Keeps the per-death lost sweep O(that worker's entries)
        # instead of a full object-table scan per worker exit (and exactly
        # zero for clusters that never touch the plane).
        self._device_index: dict[str, set] = {}
        # oid -> expiry: freed refs whose late advertises must not
        # resurrect directory entries (see _p_free_objects)
        self.freed_tombstones: dict[str, float] = {}
        self._tombstone_prune_at = 0.0
        # Task-event ring (reference task_event_buffer.h -> GCS task
        # events): feeds ray_tpu.timeline() and the state list APIs.
        self.task_events: deque = deque(maxlen=100_000)
        self.pending: deque[TaskSpec] = deque()
        # task_id -> {"spec", "node_id", "worker_id"}
        self.dispatched: dict[str, dict] = {}
        self.actors: dict[str, _ActorEntry] = {}
        self.named_actors: dict[tuple, str] = {}
        self.pgs: dict[str, dict] = {}
        self.pg_bundles: dict[tuple, dict] = {}  # (pg_id, idx) -> {node, available, reserved}
        self.kv: dict[tuple, bytes] = {}
        # Job table (reference gcs_job_manager + dashboard job_manager.py:60):
        # submission_id -> {entrypoint, status, message, node_id, start/end,
        # metadata, runtime_env}. Driver subprocesses run on a node agent.
        self.jobs: dict[str, dict] = {}
        # (metric name, sorted tag tuple) -> aggregated series
        self.metrics: dict[tuple, dict] = {}
        # Histogram bucket boundaries, registered ONCE per name by
        # `histogram_decl` records (observe records carry values only —
        # shipping the boundary list per observation bloated every flush
        # batch once the tracing plane added hot-path histograms).
        self._hist_bounds: dict[str, list] = {}
        # Tracing plane (README "Tracing & timeline"): trace_id -> {spans,
        # start, last, name, root_done, dirty} in arrival order, bounded by
        # RT_TRACE_MAX_TRACES (oldest evicted, persisted first). Served by
        # list_traces/get_trace, `ray-tpu timeline`, /api/traces.
        self.traces: dict[str, dict] = {}
        self._trace_sweep_task: Optional[asyncio.Task] = None
        # Evicted-but-unpersisted traces awaiting the persistence sweep.
        # BOUNDED: under full-sampling overload (every task its own trace)
        # evictions arrive at task rate, and persisting each inline was
        # measured at ~3x task-throughput collapse on a 1-core box — the
        # sweep drains a bounded batch per tick and sheds the rest (ring
        # discipline, same as the flight recorder).
        self._evicted_traces: deque = deque(maxlen=256)
        # Cluster event plane (README "Cluster events"): lifecycle events
        # in a bounded arrival-order ring (seq = arrival order, minted
        # here), plus a per-entity secondary index so "what happened to
        # actor X" is O(that entity's events). Settled events persist as
        # segmented JSONL through the storage plane (_event_sweep).
        self.events: deque = deque()
        self._event_seq = 0  # next seq to mint; snapshot/restore-durable
        self._event_index: dict[str, deque] = {}
        self._event_sweep_task: Optional[asyncio.Task] = None
        # Events awaiting segment persistence (bounded; a long backend
        # outage sheds OLDEST and counts them into _events_dropped).
        self._evseg_buf: list = []
        self._evseg_tail_written = -1  # last seq the current.jsonl tail has
        self._events_dropped = 0
        # task_id -> (force, expiry), for cancels that land while the task is
        # queued or mid-dispatch (neither pending nor dispatched yet).
        # Entries expire so cancels racing completion (or actor-method refs
        # that never pass through scheduling) can't leak or poison a later
        # lineage reconstruction of the same task_id.
        self.cancelled: dict[str, tuple[bool, float]] = {}
        self._persist_dirty = False
        import threading as _threading

        self._persist_io_lock = _threading.Lock()
        # Serializes event-segment writes: the sweep's executor job vs
        # stop()'s synchronous final flush (same shape as the snapshot
        # path's _persist_io_lock — unordered cross-thread current.jsonl
        # writes could lose the newest tail to a stale one). The watermark
        # ORDERS them: a writer whose coverage is below what already
        # landed skips the current.jsonl rewrite (locks alone only
        # serialize; a stale writer acquiring second would still win).
        self._event_io_lock = _threading.Lock()
        self._evseg_current_hi = -1  # newest seq current.jsonl covers
        # task_id -> (task_done payload, expiry): completions whose task_done
        # beat the dispatch *reply* (worker reports straight to the
        # controller; the agent's reply rides another connection). Replayed
        # by _dispatched once the dispatch bookkeeping exists — otherwise
        # the late-arriving entry would zombify and leak its resources.
        self.early_done: dict[str, tuple[dict, float]] = {}
        self._sched_wakeup = asyncio.Event()
        self._tasks: list[asyncio.Task] = []
        self._stopping = False
        self.port = 0
        # Worker leases (reference NormalTaskSubmitter lease pools,
        # normal_task_submitter.cc:296): owners lease workers by scheduling
        # class and push tasks to them DIRECTLY; the controller only accounts
        # resources and brokers worker acquisition. lease_id -> entry.
        self.leases: dict[str, dict] = {}
        self._last_need_push = 0.0
        self._lease_waiters = 0  # parked lease requests (fair-share signal)
        # Parked lease requests waiting for capacity: woken the moment a
        # lease returns / resources free instead of polling on a timer
        # (the 20ms poll sat directly on multi-client handoff latency).
        self._lease_waiter_futs: list[asyncio.Future] = []
        # node_id -> warm returned leases: a returned lease's worker slot
        # stays 'leased' at the agent for lease_idle_s, so a matching
        # regrant (the multi-client handoff hot path) is pure controller
        # bookkeeping — no agent round trip, and usually a cached owner
        # connection. Entries: {worker_id, address, demand, expires}.
        self.lease_pool: dict[str, list] = {}
        self._lease_pool_size = 0
        # Observability for the direct-dispatch plane (asserted by tests):
        # grants split by warm-pool hit vs agent acquisition, plus returns.
        self.lease_grants = 0
        self.lease_pool_hits = 0
        self.lease_returns = 0
        # (owner, lease_entry, expiry): reasserted leases whose node agent
        # hasn't re-registered yet (controller restart FT).
        self._parked_reasserts: list[tuple] = []
        # task_id -> (node_id, raw resources): pre-restart in-flight tasks
        # whose capacity was charged from an agent's inventory report.
        self._reconciled_busy: dict[str, tuple] = {}
        # worker_ids that ever hosted an actor instance: the fate-sharing
        # reaper must recognize an actor owner even after its entry's
        # worker_id was cleared by the death bookkeeping.
        self._actor_host_workers: set[str] = set()
        # task_id -> (spec, demand, nid): specs sent in a dispatch_batch
        # whose per-spec `dispatched` push hasn't landed yet. Entries left
        # after the batch call resolves (agent/conn death) are requeued.
        self._pending_dispatch: dict[str, tuple] = {}
        # owner worker_id -> buffered object_ready items: completions are
        # notified in batched `objects_ready` frames (one per owner per
        # event-loop burst) instead of one push per oid.
        self._ready_bufs: dict[str, list] = {}
        # Stall-detection plane (README "Stall detection & watchdogs"):
        # ring of StallReports forwarded by node agents (worker watchdogs +
        # agent backstops) and train controllers; served by list_stalls /
        # `ray-tpu stalls`, counted into rt_stalls_total{stage}.
        self.stalls: deque = deque(maxlen=512)
        # node_id -> (task_id -> progress-silence seconds, received-at):
        # per-task beacon ages riding agent heartbeats, so task_status can
        # answer "how long has the producer been silent".
        self._task_beacons: dict[str, tuple] = {}
        # Telemetry plane (README "Telemetry & profiling"): (node_id,
        # series, worker_prefix) -> _SeriesRing, fed by the `telemetry`
        # batches riding agent heartbeats plus the controller's own
        # self-sample tick. Series quiet past RT_TELEMETRY_WINDOW_S age
        # out (a dead agent's series disappear instead of freezing).
        self.telemetry: dict[tuple, _SeriesRing] = {}
        self._telem_prune_at = 0.0
        self._telem_skew: dict[str, float] = {}  # node -> sticky rebase
        self._telem_task: Optional[asyncio.Task] = None
        # Controller self-telemetry, no agent involved: per-RPC-method
        # latency/count histograms (method -> [count, sum, buckets]) —
        # accumulated inline in _on_request (two perf_counter reads + one
        # bisect; always on) — and the event-loop lag gauge (measured by
        # the self-sample tick, None while telemetry is unarmed).
        self._rpc_stats: dict[str, list] = {}
        self._loop_lag: Optional[float] = None
        # node_id -> latest minted incarnation. Survives the NodeState
        # (incremented across SUSPECT->DEAD->rejoin), so a zombie agent
        # from ANY previous life is fenced, not just the last one.
        self.node_incarnations: dict[str, int] = {}
        # Observability for the fencing path (asserted by chaos tests).
        self.stale_incarnation_rejections = 0

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        if CONFIG.controller_persist_dir:
            self._restore_state()
            self._tasks.append(asyncio.ensure_future(self._persist_loop()))
            if any(e.state == "RECOVERING" for e in self.actors.values()):
                self._tasks.append(
                    asyncio.ensure_future(self._reconcile_recovering()))
        # Event-plane seq fencing: a restored (or re-started-into-session)
        # head must mint seqs ABOVE anything already persisted, or fresh
        # events would collide with segment history (pinned by test).
        self._restore_event_seq()
        self.port = await self.server.start(host, port)
        self._tasks.append(asyncio.ensure_future(self._schedule_loop()))
        self._tasks.append(asyncio.ensure_future(self._health_loop()))
        from ray_tpu._private import telemetry as _telemetry

        if _telemetry.interval_s() > 0:
            self._telem_task = asyncio.ensure_future(self._self_sample_loop())
            self._tasks.append(self._telem_task)
        return self.port

    async def _reconcile_recovering(self):
        """Grace window after a restart for agents to re-report surviving
        actor workers; whatever never shows up is re-created (detached, or
        owner re-registered) or declared DEAD (reference: GCS restart
        reconciliation, gcs_actor_manager restart-on-node-report)."""
        await asyncio.sleep(max(
            2.0, CONFIG.heartbeat_interval_s * CONFIG.num_heartbeats_timeout))
        for aid, ent in list(self.actors.items()):
            if ent.state != "RECOVERING":
                continue
            owner_alive = ent.spec.owner_id in self.client_conns
            if ent.spec.lifetime == "detached" or owner_alive:
                ent.state = "PENDING"
                self.pending.append(ent.spec)
                logger.info("actor %s did not survive the controller "
                            "restart; re-creating", aid[:8])
            else:
                from ray_tpu._private.serialization import dumps_oob

                ent.state = "DEAD"
                self._emit_event(
                    "actor_death",
                    f"actor {aid[:12]} did not survive the controller "
                    f"restart (worker and owner gone)", entity=(aid,))
                h, bufs = dumps_oob({
                    "type": "ActorDiedError",
                    "message": f"actor {aid[:12]} did not survive the "
                               f"controller restart (worker and owner gone)"
                               + self._event_hint(aid)})
                ent.death_cause = [h, *bufs]
                if ent.name:
                    # Free the name like every other death path does
                    # (_bury_actor), or get_actor(name) resolves to a corpse.
                    self.named_actors.pop((ent.namespace, ent.name), None)
                self._mark_dirty()
                self._publish("actor", {"actor_id": aid, "state": "DEAD"})
            # Either way: wake get_actor_info callers parked on RECOVERING.
            for fut in ent.waiters:
                if not fut.done():
                    fut.set_result(None)
            ent.waiters.clear()
        self._kick()

    # ------------------------------------------------------- persistence
    # Reference: src/ray/gcs/store_client/redis_store_client.h — GCS state
    # survives restarts in Redis. Here: pickled snapshots (atomic replace)
    # of the DURABLE domains: KV, named-actor registry + actor creation
    # specs, and PG definitions. On restore, actors re-queue as creation
    # specs and run again once nodes join (their in-memory state restarts —
    # reference raylets outlive the GCS so theirs keep running; our agents
    # share fate with the controller, so re-creation is the contract).

    def _persist_path(self) -> str:
        # controller_persist_dir may be any storage-plane URI (local path,
        # local://, sim://) — snapshots ride the same pluggable backend as
        # train/tune/workflow checkpoints (README "Checkpointing & storage").
        from ray_tpu import storage

        return storage.join(CONFIG.controller_persist_dir,
                            "controller_state.pkl")

    def _mark_dirty(self):
        self._persist_dirty = True

    def _restore_state(self):
        import pickle
        import time as _time

        from ray_tpu import storage

        path = self._persist_path()
        # Read with a short transient-retry budget: a blipping REMOTE
        # persist backend (sim://, future object stores) must not be
        # mistaken for corruption — quarantining an intact snapshot would
        # let the persist loop later overwrite it with empty state.
        data = None
        delay = 0.1
        for attempt in range(4):
            try:
                if not storage.exists(path):
                    return
                data = storage.get_bytes(path)
                break
            except storage.StorageTransientError:
                if attempt == 3:
                    logger.exception(
                        "controller: persist backend unreachable reading "
                        "%s; starting fresh WITHOUT quarantining (the "
                        "snapshot may be intact)", path)
                    return
                _time.sleep(delay)
                delay *= 2
        try:
            snap = pickle.loads(data)
        except Exception:
            # A corrupt/truncated snapshot must not crash-loop the
            # controller: quarantine the bad file (kept for forensics
            # under a .corrupt suffix) and start fresh — re-persist will
            # atomically write a good one.
            logger.exception(
                "controller: persisted state unreadable; quarantining %s "
                "and starting fresh", path)
            try:
                storage.rename(path, path + ".corrupt")
            except Exception:
                logger.exception("controller: quarantine rename failed")
            return
        self.kv = snap.get("kv", {})
        self.named_actors = snap.get("named_actors", {})
        self._event_seq = max(self._event_seq,
                              int(snap.get("events_seq") or 0))
        if snap.get("session_id"):
            # Adopt the previous incarnation's session: agents/workers that
            # survived the restart registered their shm segments under it.
            self.session_id = snap["session_id"]
        for item in snap.get("actors", []):
            aid, spec = item[0], item[1]
            ent = _ActorEntry(spec)
            ent.restarts_used = item[2] if len(item) > 2 else 0
            # RECOVERING: the actor's worker may have SURVIVED the restart
            # (agents outlive the controller). Wait for agents to re-report
            # inventory; _reconcile_recovering re-creates whatever never
            # shows up (reference: GCS restart reconciliation before any
            # actor restart decisions).
            ent.state = "RECOVERING"
            self.actors[aid] = ent
        for pid, pg in snap.get("pgs", {}).items():
            self.pgs[pid] = {"state": "PENDING",
                             "bundles_raw": pg["bundles_raw"],
                             "strategy": pg["strategy"], "name": pg.get("name")}
        logger.info(
            "controller: restored %d kv entries, %d actors, %d pgs from %s",
            len(self.kv), len(snap.get("actors", [])), len(self.pgs), path)

    async def _persist_loop(self):
        while True:
            await asyncio.sleep(0.5)
            if not self._persist_dirty:
                continue
            self._persist_dirty = False
            snap = self._build_snapshot()  # consistent view, on the loop
            try:
                # The pickle+write happens OFF the event loop: a large KV
                # must not stall heartbeats/scheduling for the write.
                await asyncio.to_thread(self._dump_snapshot, snap)
            except Exception:
                self._persist_dirty = True  # acknowledged state must retry
                logger.exception("controller: persist failed")

    def _build_snapshot(self) -> dict:
        return {
            "session_id": self.session_id,
            # names only for actors that are themselves persisted — a
            # dangling name->id mapping would break name reuse after restore
            "kv": dict(self.kv),
            "named_actors": {
                k: aid for k, aid in self.named_actors.items()
                if (e := self.actors.get(aid)) is not None
                and e.state != "DEAD"},
            # ALL live actors (not just detached): agents outlive a
            # controller restart, so a surviving worker re-binds its actor
            # entry on re-registration; only actors whose workers really
            # died get re-created (detached / owner-alive) or declared DEAD
            # by the reconcile sweep.
            "actors": [(aid, ent.spec, ent.restarts_used)
                       for aid, ent in self.actors.items()
                       if ent.state != "DEAD"],
            "pgs": {pid: {"bundles_raw": pg["bundles_raw"],
                          "strategy": pg["strategy"], "name": pg.get("name")}
                    for pid, pg in self.pgs.items()},
            # Event-plane seq watermark: restore resumes minting above it
            # (belt; _restore_event_seq's segment scan is the braces for
            # seqs minted after the last snapshot).
            "events_seq": self._event_seq,
        }

    def _dump_snapshot(self, snap: dict):
        import pickle

        from ray_tpu import storage

        # Serializes the threaded persist-loop dump against stop()'s final
        # synchronous flush: the LAST writer must be the newest snapshot.
        # storage.put is atomic on every backend (tmp + rename on the
        # local fs), preserving the old atomic-replace contract.
        with self._persist_io_lock:
            storage.put(self._persist_path(),
                        pickle.dumps(snap, protocol=5))

    def _write_snapshot(self):
        self._dump_snapshot(self._build_snapshot())

    async def stop(self):
        self._stopping = True
        if CONFIG.controller_persist_dir and self._persist_dirty:
            try:
                self._write_snapshot()  # acknowledged writes survive shutdown
            except Exception:
                logger.exception("controller: final persist failed")
        # Final event flush: history already ingested must not lose its
        # last sweep-tick's worth to the shutdown (durable = durable).
        try:
            d = self._event_dir()
            if d is not None and self._evseg_buf:
                tail_hi = self._evseg_buf[-1]["seq"]
                if tail_hi > self._evseg_tail_written:
                    self._persist_event_segments_sync(
                        d, [], list(self._evseg_buf),
                        max(1, int(CONFIG.events_keep_segments)), 0)
                    self._evseg_tail_written = tail_hi
        except Exception:
            logger.debug("controller: final event flush failed",
                         exc_info=True)
        for nid, conn in list(self.node_conns.items()):
            try:
                await conn.push("shutdown")
            except Exception:
                pass
        for t in self._tasks:
            t.cancel()
        await self.server.stop()

    # ------------------------------------------------------------------ RPC
    async def _on_request(self, conn: rpc.Connection, method: str, a: dict):
        handler = getattr(self, f"_h_{method}", None)
        if handler is None:
            raise rpc.RpcError(f"controller: unknown method {method}")
        # Controller self-telemetry: per-method handler latency histogram
        # (README "Telemetry & profiling" — the direct input to the
        # control-plane scale harness, ROADMAP item 3). Always on: two
        # perf_counter reads + a bisect over 7 bounds per request, cheap
        # against any handler body; exposed via /metrics and get_metrics.
        t0 = time.perf_counter()
        try:
            return await handler(conn, a)
        finally:
            dt = time.perf_counter() - t0
            st = self._rpc_stats.get(method)
            if st is None:
                st = self._rpc_stats[method] = [
                    0, 0.0, [0] * (len(_RPC_BOUNDS) + 1)]
            st[0] += 1
            st[1] += dt
            st[2][bisect.bisect_left(_RPC_BOUNDS, dt)] += 1

    async def _on_push(self, conn: rpc.Connection, method: str, a: dict):
        handler = getattr(self, f"_p_{method}", None)
        if handler is None:
            logger.warning("controller: unknown push %s", method)
            return
        await handler(conn, a)

    def _on_conn_close(self, conn: rpc.Connection):
        if self._stopping:
            return
        kind = conn.meta.get("kind")
        if kind == "node":
            nid = conn.meta["node_id"]
            node = self.nodes.get(nid)
            if node is None or conn.meta.get("incarnation") != node.incarnation:
                # A previous incarnation's connection closing (the agent
                # already re-registered on a fresh one): not a liveness
                # event for the CURRENT life.
                return
            asyncio.ensure_future(self._node_suspect(nid, conn))
        elif kind == "client":
            wid = conn.meta.get("worker_id")
            self.client_conns.pop(wid, None)
            if conn.meta.get("log_sub") and not self._any_log_sub():
                # Last subscriber left: stop agents shipping log lines.
                asyncio.ensure_future(self._push_log_sub_state(False))
            asyncio.ensure_future(self._reap_owner_leases(wid))
            asyncio.ensure_future(
                self._reap_owned_actors(wid, conn.meta.get("mode")))
            asyncio.ensure_future(self._reap_borrows(wid))
            asyncio.ensure_future(self._client_device_sweep(wid))

    async def _client_device_sweep(self, wid: str):
        """A client (driver or worker) connection closed: after a short
        grace (the close may be a transient drop — reconnects re-register
        on a fresh conn), device entries the process produced go LOST so
        consumers get the fast sticky ObjectLostError instead of a connect
        timeout per read. Worker processes are also covered by the agent's
        worker_died report; this path is the only one that reaches DRIVER
        producers."""
        if not self._device_index.get(wid):
            return
        await asyncio.sleep(max(1.0, CONFIG.node_suspect_grace_s))
        conn = self.client_conns.get(wid)
        if conn is not None and not conn.closed:
            return  # re-registered: the producer (and its pins) live on
        await self._device_objects_lost(wid, "process disconnected")

    async def _reconcile_reported_worker(self, nid: str, node: "NodeState", w: dict):
        """One inventory entry from a re-registering agent (controller
        restart FT). Actors whose workers survived re-bind in place —
        running calls on their direct pipes never noticed the outage."""
        aid = w.get("actor_id")
        held = w.get("resources")
        if aid:
            ent = self.actors.get(aid)
            rebindable = (
                ent is not None
                and (ent.state in ("RECOVERING", "PENDING")
                     # RESTARTING re-binds only while the re-creation is
                     # still QUEUED (cancellable); once it dispatched, a
                     # second instance is already being built elsewhere.
                     or (ent.state == "RESTARTING"
                         and ent.spec in self.pending)))
            if ent is not None and ent.state == "ALIVE" \
                    and ent.worker_id == w["worker_id"]:
                # Already bound to exactly this worker (raced reconcile
                # paths): refresh the address and make sure the (possibly
                # fresh) NodeState carries the charge.
                ent.node_id = nid
                ent.address = tuple(w["address"])
                if held and not ent.resources_held:
                    node.available.subtract(ResourceSet(_raw=held))
                    ent.resources_held = True
                return
            if ent is None:
                # Unknown actor (e.g. restart without persistence): not
                # provably stale — leave the worker alone like before.
                return
            if not rebindable:
                # Split-brain zombie: the actor is DEAD, already
                # restarted/rebound elsewhere, or its re-creation already
                # dispatched — and now an old instance's worker resurfaces
                # on a returning node, still serving its pipes. Exactly one
                # instance may live: reap the resurfaced one.
                await self._reap_stale_worker(nid, w["worker_id"], aid,
                                              "resurfaced after its restart")
                return
            try:
                self.pending.remove(ent.spec)  # un-queue a re-creation
            except ValueError:
                pass
            ent.state = "ALIVE"
            ent.node_id = nid
            ent.worker_id = w["worker_id"]
            ent.address = tuple(w["address"])
            self._actor_host_workers.add(w["worker_id"])
            if held and not ent.resources_held:
                node.available.subtract(ResourceSet(_raw=held))
                ent.resources_held = True
            for fut in ent.waiters:
                if not fut.done():
                    fut.set_result(None)
            ent.waiters.clear()
            self._publish("actor", {"actor_id": aid, "state": "ALIVE"})
            logger.info("actor %s re-bound to surviving worker %s",
                        aid[:8], w["worker_id"][:8])
            self._emit_event(
                "actor_ready",
                f"actor {aid[:12]} re-bound to surviving worker "
                f"{w['worker_id'][:12]}",
                entity=(aid, w["worker_id"]), node_id=nid,
                attrs={"rebound": True})
        elif w.get("state") == "busy" and held:
            # A controller-dispatched task still running; charge its
            # resources so the scheduler doesn't oversubscribe the node,
            # and remember the charge so its task_done (or the node's
            # death) releases it — this controller never dispatched the
            # task, so the normal release path can't.
            node.available.subtract(ResourceSet(_raw=held))
            if w.get("task_id"):
                self._reconciled_busy[w["task_id"]] = (nid, dict(held))

    async def _reap_stale_worker(self, nid: str, wid: str, aid: str,
                                 why: str):
        """Kill a resurfaced actor instance whose entry no longer points at
        it (exactly one instance may live). ONE implementation for both
        reconcile paths so the zombie-reap protocol cannot drift."""
        nconn = self.node_conns.get(nid)
        if nconn is None or nconn.closed:
            return
        logger.warning(
            "actor %s: stale instance on returning node %s (%s); killing "
            "the zombie worker %s", aid[:8], nid[:8], why, wid[:8])
        try:
            await nconn.push("kill_worker", worker_id=wid)
        except Exception:
            pass

    async def _p_reassert_leases(self, conn, a):
        """An owner re-declares leases it held across a controller restart
        (the lease ids live with the owner; the agent's inventory only
        shows 'leased' slots). A lease whose node hasn't re-registered YET
        is parked and retried on node registration — owners and agents
        reconnect independently, so in ~half of restarts the one-shot
        reassert beats the agent; dropping it would oversubscribe the node
        and leak the leased worker."""
        owner = a.get("owner_id")
        for ent in a.get("leases") or ():
            if not self._apply_reassert(owner, ent):
                self._parked_reasserts.append(
                    (owner, ent, time.monotonic() + 30.0))
        logger.info("owner %s reasserted %d leases",
                    (owner or "?")[:8], len(a.get("leases") or ()))

    def _apply_reassert(self, owner, ent) -> bool:
        """Returns False if the lease's node is not (yet) registered."""
        lid = ent["lease_id"]
        if lid in self.leases:
            return True
        nid = ent.get("node_id")
        node = self.nodes.get(nid)
        if node is None or not node.alive:
            return False
        inc = ent.get("incarnation")
        if inc is not None and inc != node.incarnation:
            # Fenced: the lease was granted against a previous life of this
            # node — its worker died with that life, so the lease is dead on
            # arrival (charging its resources would oversubscribe the fresh
            # life). Consumed, not parked; the owner fails its in-flight
            # specs over on the invalidation.
            self.stale_incarnation_rejections += 1
            logger.warning(
                "rejected stale-incarnation lease %s for node %s "
                "(incarnation %s, current %s)", lid[:8], nid[:8], inc,
                node.incarnation)
            self._emit_event(
                "incarnation_fenced",
                f"rejected lease {lid[:8]} reasserted against node "
                f"{nid[:8]}'s previous life (incarnation {inc}, current "
                f"{node.incarnation})",
                entity=(lid, nid, owner), node_id=nid,
                attrs={"stale": inc, "current": node.incarnation})
            oconn = self.client_conns.get(owner)
            if oconn is not None and not oconn.closed:
                try:
                    oconn.push_threadsafe("lease_invalid", lease_id=lid,
                                          cause="stale node incarnation")
                except Exception:
                    pass
            return True
        demand = ResourceSet(_raw=ent["resources"])
        try:
            self._consume_for(nid, ent["strategy"], demand)
        except Exception:
            node.available.subtract(demand)
        self.leases[lid] = {
            "owner": owner,
            "node_id": nid,
            "worker_id": ent["worker_id"],
            "address": tuple(ent["address"]) if ent.get("address") else None,
            "demand": demand.raw(),
            "strategy": ent["strategy"],
            "incarnation": node.incarnation,
        }
        return True

    def _retry_parked_reasserts(self):
        now = time.monotonic()
        self._parked_reasserts = [
            (owner, ent, exp) for owner, ent, exp in self._parked_reasserts
            if exp > now and not self._apply_reassert(owner, ent)]

    async def _reap_borrows(self, wid: str):
        """A dead borrower can never drop its borrows: remove it from every
        borrower set; the dying-object sweep frees entries it was pinning
        once their grace TTL passes."""
        if not wid:
            return
        for ent in self.objects.values():
            ent.borrowers.discard(wid)

    # ------------------------------------------------------- registration
    async def _h_register(self, conn, a):
        incarnation = None
        if a["kind"] == "node":
            nid = a["node_id"]
            # Mint the next incarnation for this node_id. Every registration
            # is a new life; messages and conn-close events carrying an
            # older incarnation are fenced from then on.
            incarnation = self.node_incarnations.get(nid, 0) + 1
            self.node_incarnations[nid] = incarnation
            conn.label = conn.label or "node"
            existing = self.nodes.get(nid)
            if existing is not None and existing.liveness in ("ALIVE", "SUSPECT"):
                # The agent reconnected within the grace window (or raced
                # its own connection loss): reconcile IN PLACE. The
                # NodeState keeps its resource accounting; the inventory
                # diff below releases whatever died during the blip.
                node = existing
                was = node.liveness
                node.liveness = "ALIVE"
                node.address = tuple(a["address"])
                if a.get("labels") is not None:  # {} clears, like fresh path
                    node.labels = a["labels"]
                node.incarnation = incarnation
                node.last_beat = time.monotonic()
                # The agent may have restarted with a DIFFERENT resource
                # config: apply the capacity delta while preserving the
                # frozen in-use accounting (available can go negative on a
                # shrink; fits() then refuses placements until work drains).
                new_total = ResourceSet(_raw=a["resources"])
                if new_total.raw() != node.total.raw():
                    node.available.add(new_total)
                    node.available.subtract(node.total)
                    node.total = new_total
                self.node_conns[nid] = conn
                conn.meta.update(kind="node", node_id=nid,
                                 incarnation=incarnation)
                await self._reconcile_returned_node(
                    nid, node, a.get("workers") or ())
                logger.info("node %s re-registered (was %s) as incarnation "
                            "%d; reconciled in place", nid[:8], was,
                            incarnation)
                self._emit_event(
                    "node_reconciled",
                    f"node {nid[:8]} re-registered (was {was}) and "
                    f"reconciled in place",
                    entity=(nid,), node_id=nid,
                    attrs={"incarnation": incarnation, "was": was})
            else:
                node = NodeState(nid, tuple(a["address"]),
                                 ResourceSet(_raw=a["resources"]), a.get("labels"))
                node.incarnation = incarnation
                node.last_beat = time.monotonic()
                self.nodes[nid] = node
                self.node_conns[nid] = conn
                conn.meta.update(kind="node", node_id=nid,
                                 incarnation=incarnation)
                # Re-registration after a controller restart (or a return
                # after DEAD): the agent reports its live worker inventory
                # so this controller can rebuild accounting — bind
                # recovering actors to their still-running workers; charge
                # dedicated/busy slots' resources. Leased slots are charged
                # by their OWNER's reassert_leases (the owner knows the
                # lease ids; the agent doesn't).
                for w in a.get("workers") or ():
                    await self._reconcile_reported_worker(nid, node, w)
                logger.info("node %s registered with %s (incarnation %d)",
                            nid[:8], node.total.to_dict(), incarnation)
                self._emit_event(
                    "node_register",
                    f"node {nid[:8]} registered with {node.total.to_dict()}",
                    entity=(nid,), node_id=nid,
                    attrs={"incarnation": incarnation})
            if self._parked_reasserts:
                self._retry_parked_reasserts()
            self._retry_pending_pgs()
            self._kick()
            self._publish("node", {"node_id": nid, "alive": True,
                                   "liveness": "ALIVE",
                                   "resources": node.total.to_dict()})
        else:
            wid = a["worker_id"]
            self.client_conns[wid] = conn
            conn.label = conn.label or "client"
            conn.meta.update(kind="client", worker_id=wid,
                             mode=a.get("mode"),
                             address=tuple(a["address"]) if a.get("address") else None)
        return {"session_id": self.session_id, "config": CONFIG.snapshot(),
                "log_sub": self._any_log_sub(), "incarnation": incarnation}

    def _fenced_node(self, conn, a) -> Optional[NodeState]:
        """Resolve the node a message is about, REJECTING messages from a
        previous incarnation (reference: raylet registration epochs; SWIM
        incarnation numbers). The incarnation comes from the payload echo
        when present, else from the connection's registration meta — so a
        zombie agent that never re-registered is fenced by its old conn."""
        nid = a.get("node_id") or (conn.meta.get("node_id")
                                   if conn is not None else None)
        if nid is None:
            return None
        node = self.nodes.get(nid)
        if node is None:
            return None
        inc = a.get("incarnation")
        if inc is None and conn is not None:
            inc = conn.meta.get("incarnation")
        if inc is not None and inc != node.incarnation:
            self.stale_incarnation_rejections += 1
            logger.warning(
                "rejected stale-incarnation message for node %s "
                "(incarnation %s, current %s)", nid[:8], inc,
                node.incarnation)
            self._emit_event(
                "incarnation_fenced",
                f"rejected a message from node {nid[:8]}'s previous life "
                f"(incarnation {inc}, current {node.incarnation})",
                entity=(nid,), node_id=nid,
                attrs={"stale": inc, "current": node.incarnation})
            return None
        return node

    async def _p_heartbeat(self, conn, a):
        node = self._fenced_node(conn, a)
        if node is not None and node.liveness != "DEAD":
            node.last_beat = time.monotonic()
            if "shm_used" in a:
                node.shm_used = a["shm_used"]
            beacons = a.get("beacons")
            if beacons:
                self._task_beacons[a["node_id"]] = (beacons, time.monotonic())
            else:
                self._task_beacons.pop(a.get("node_id"), None)
            telem = a.get("telemetry")
            if telem:
                self._ingest_telemetry(a["node_id"], telem)
            evs = a.get("events")
            if evs:
                self._ingest_events(evs, default_node=a["node_id"])

    # ---------------------------------------------------------- scheduling
    def _kick(self):
        self._sched_wakeup.set()
        if self._lease_waiter_futs:
            self._kick_leases()

    def _kick_leases(self):
        """Wake parked lease requests (capacity may have freed)."""
        waiters, self._lease_waiter_futs = self._lease_waiter_futs, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    async def _schedule_loop(self):
        while True:
            await self._sched_wakeup.wait()
            self._sched_wakeup.clear()
            await self._schedule_once()

    async def _schedule_once(self):
        # Single pass over the queue; tasks that can't be placed stay queued.
        # Placements are grouped per node and dispatched as ONE batched RPC
        # per node per pass (the agent fans out worker acquisition
        # internally), run concurrently (ensure_future) so one node's slow
        # worker acquisition cannot stall cluster-wide placement (the agent
        # may wait up to worker_register_timeout_s for a free worker).
        still_pending: deque[TaskSpec] = deque()
        # Demand signatures that already failed to place in THIS pass: later
        # FIFO tasks with the same shape can't place either — skip their
        # pick_node scan (reference caches by SchedulingClass; keeps a burst
        # of N queued tasks from costing O(N) scans per completion).
        failed_sigs: set = set()
        by_node: dict[str, list] = {}  # nid -> [(spec, demand)]
        while self.pending:
            spec = self.pending.popleft()
            if self._consume_cancel(spec.task_id) is not None:
                await self._finish_cancelled(spec)
                continue
            sig = (tuple(sorted(spec.resources.items())), spec.strategy.kind,
                   spec.strategy.node_id, spec.strategy.soft,
                   spec.strategy.pg_id, spec.strategy.pg_bundle_index)
            if sig in failed_sigs:
                still_pending.append(spec)
                continue
            demand = ResourceSet(_raw=spec.resources)
            nid = pick_node(demand, spec.strategy, self.nodes, self.pg_bundles,
                            preferred=self._locality_nodes(spec))
            if nid is None:
                failed_sigs.add(sig)
                still_pending.append(spec)
                continue
            self._consume(nid, spec, demand)
            by_node.setdefault(nid, []).append((spec, demand))
        self.pending.extend(still_pending)
        for nid, items in by_node.items():
            asyncio.ensure_future(self._dispatch_batch_bg(nid, items))
        if still_pending:
            self._maybe_push_need_resources()

    def _locality_nodes(self, spec: TaskSpec) -> dict:
        """node_id -> bytes of this spec's ref arguments already resident
        there (feeds pick_node's locality preference; reference
        dependency_manager.h's locality-aware dispatch)."""
        out: dict[str, int] = {}
        addr_to_node = host_to_node = None
        for oid in spec.ref_arg_oids():
            ent = self.objects.get(oid)
            if ent is None or not ent.holders or not ent.size:
                continue
            if addr_to_node is None:
                addr_to_node = {}
                host_counts: dict[str, list] = {}
                for nid, n in self.nodes.items():
                    if not n.alive:
                        continue
                    addr_to_node[tuple(n.address)] = nid
                    host_counts.setdefault(n.address[0], []).append(nid)
                # Driver puts advertise the driver's own server address
                # (host + ephemeral port), not a node agent's: fall back to
                # host matching when exactly one node lives on that host.
                host_to_node = {h: nids[0] for h, nids in host_counts.items()
                                if len(nids) == 1}
            for h in ent.holders:
                nid = addr_to_node.get(tuple(h)) or host_to_node.get(h[0])
                if nid is not None:
                    out[nid] = out.get(nid, 0) + ent.size
        return out

    async def _dispatch_batch_bg(self, nid: str, items: list):
        """One `dispatch_batch` RPC carries every spec this scheduling pass
        placed on `nid` (O(1) frames per hop for an async burst of N
        tasks). The agent acquires workers for all specs concurrently and
        reports EACH spec eagerly via a `dispatched` push the moment its
        acquisition resolves — a fast acquisition never waits for a cold
        worker spawn sharing its batch. Pushes ride the same ordered
        connection as the call reply, so every push lands before the reply:
        the reply (or its failure) is purely the barrier after which
        still-pending specs are provably unreported and safe to requeue."""
        conn = self.node_conns.get(nid)
        if conn is None or conn.closed:
            for spec, demand in items:
                self._release(nid, spec, demand)
                self.pending.append(spec)
            self._kick()
            return
        for spec, demand in items:
            self._pending_dispatch[spec.task_id] = (spec, demand, nid)
        try:
            await conn.call("dispatch_batch", specs=[s for s, _ in items])
        except Exception:
            # Transport failure (RpcError, reset, broken pipe): leftovers
            # are requeued below; a raw OSError must not kill this
            # fire-and-forget task and leak capacity.
            pass
        requeued = False
        for spec, demand in items:
            if self._pending_dispatch.pop(spec.task_id, None) is not None:
                self._release(nid, spec, demand)
                self.pending.append(spec)
                requeued = True
        if requeued:
            self._kick()

    async def _p_dispatched(self, conn, a):
        """Per-spec eager dispatch report from an agent (see
        _dispatch_batch_bg). Exceptions here are isolated per spec — one
        bad early_done replay must not strand its batch siblings."""
        ent = self._pending_dispatch.pop(a["task_id"], None)
        if ent is None:
            return  # batch barrier already failed this spec over; or dup
        spec, demand, nid = ent
        if a.get("dup"):
            # The agent already executed this task id on its direct (leased)
            # path — the spec reaching it again is an owner failover racing
            # an orphaned completion. At-most-once: don't run it twice; the
            # dedup record carries the first execution's results, so resolve
            # them exactly like a task_done (notifies the owner's refs).
            self._release(nid, spec, demand)
            try:
                await self._p_task_done(None, {
                    "task_id": spec.task_id, "attempt": spec.attempt,
                    "results": a.get("results") or [],
                    "error": a.get("error"),
                    "retryable": a.get("retryable", False), "spec": spec})
            except Exception:
                logger.exception("dedup completion replay failed for task %s",
                                 a["task_id"][:12])
            self._kick()
            return
        if not a.get("ok"):
            self._release(nid, spec, demand)
            self.pending.append(spec)
            self._kick()
            return
        try:
            await self._dispatched(nid, spec, a["worker_id"],
                                   self.node_conns.get(nid))
        except Exception:
            logger.exception("post-dispatch bookkeeping failed for task %s",
                             a["task_id"][:12])

    async def _dispatched(self, nid: str, spec: TaskSpec, worker_id: str,
                          nconn) -> None:
        """Post-dispatch bookkeeping for one successfully placed spec."""
        self.dispatched[spec.task_id] = {
            "spec": spec, "node_id": nid, "worker_id": worker_id}
        if spec.kind == ACTOR_CREATE:
            ent = self.actors.get(spec.actor_id)
            if ent is None or ent.state == "DEAD":
                # kill() raced the creation dispatch: reap the fresh worker
                # and give the resources back instead of resurrecting. A
                # task_done that beat the dispatch report is moot now —
                # drop its parked replay instead of leaving it to the TTL.
                self.dispatched.pop(spec.task_id, None)
                self.early_done.pop(spec.task_id, None)
                self._release(nid, spec, ResourceSet(_raw=spec.resources))
                try:
                    await nconn.push("kill_worker", worker_id=worker_id)
                except Exception:
                    pass
                return
            ent.node_id = nid
            ent.worker_id = worker_id
            ent.resources_held = True
        early = self.early_done.pop(spec.task_id, None)
        if early is not None:
            payload = dict(early[0])
            if payload.get("attempt", 0) != spec.attempt:
                return  # stale completion of a previous attempt: discard
            payload["_replayed"] = True
            await self._p_task_done(None, payload)
        # A cancel may have landed while the dispatch RPC was in flight
        # (worker still starting): deliver it now that we know the worker.
        if spec.task_id in self.cancelled:
            spec.max_retries = 0  # a cancelled task must never retry
            info = self.dispatched.get(spec.task_id)
            if info is not None and nconn is not None and not nconn.closed:
                force, _ = self.cancelled.pop(spec.task_id)
                try:
                    await nconn.push("cancel_task", worker_id=info["worker_id"],
                                     task_id=spec.task_id, force=force)
                except Exception:
                    pass
            # else: leave the marker parked — if the node dies the requeue
            # path consumes it in _schedule_once/_p_task_failed.

    def _consume(self, nid: str, spec: TaskSpec, demand: ResourceSet):
        if spec.strategy.kind == "PLACEMENT_GROUP":
            # PG resources were reserved from the node at PG creation.
            for (pgid, idx), b in self.pg_bundles.items():
                if pgid == spec.strategy.pg_id and b["node"] == nid and b["available"].fits(demand):
                    if spec.strategy.pg_bundle_index in (-1, idx):
                        b["available"].subtract(demand)
                        spec.strategy.pg_bundle_index = idx  # pin for release
                        return
        self.nodes[nid].available.subtract(demand)

    def _release(self, nid: str, spec: TaskSpec, demand: ResourceSet):
        if spec.strategy.kind == "PLACEMENT_GROUP":
            b = self.pg_bundles.get((spec.strategy.pg_id, spec.strategy.pg_bundle_index))
            if b is not None:
                b["available"].add(demand)
                return
        node = self.nodes.get(nid)
        if node is not None:
            node.available.add(demand)

    @staticmethod
    def _ingest_spec(conn, spec: TaskSpec) -> TaskSpec:
        """Over the in-process transport the submitter's LIVE spec arrives;
        the controller mutates accepted specs (attempt, max_retries,
        pg_bundle_index), so take a private copy. RPC connections already
        deliver fresh unpickled copies."""
        if isinstance(conn, rpc.LocalConnection):
            return spec.clone()
        return spec

    async def _h_submit_task(self, conn, a):
        spec = self._ingest_spec(conn, a["spec"])
        for oid in spec.return_object_ids():
            ent = self.objects.setdefault(oid, _ObjectEntry())
            ent.owner = spec.owner_id
        self.pending.append(spec)
        self._kick()
        return {"queued": True}

    async def _p_submit_task(self, conn, a):
        """Push variant: submitters don't need the queue ack (hot path)."""
        await self._h_submit_task(conn, a)

    async def _h_submit_tasks(self, conn, a):
        """Vectorized submit: a burst of N same-tick submissions rides one
        frame (reference NormalTaskSubmitter batches raylet RPCs). Callable
        (the ack tells the submitter the batch is durably queued — with
        coalesced writes a one-way push could be lost with a dying
        connection AFTER the submitter's flush succeeded) or push-able."""
        for spec in a["specs"]:
            spec = self._ingest_spec(conn, spec)
            for oid in spec.return_object_ids():
                ent = self.objects.setdefault(oid, _ObjectEntry())
                ent.owner = spec.owner_id
            self.pending.append(spec)
        self._kick()
        return {"queued": True}

    # Push forms (one-way; wire-compat alias for the pre-coalescing name).
    _p_submit_tasks = _h_submit_tasks
    _p_submit_batch = _h_submit_tasks

    # ------------------------------------------------------ task completion
    async def _p_task_done(self, conn, a):
        task_id = a["task_id"]
        self.cancelled.pop(task_id, None)  # completed: stale cancel marker must
        # not kill a later lineage reconstruction of the same task_id
        rec = self._reconciled_busy.pop(task_id, None)
        if rec is not None:
            # A pre-restart in-flight task finishing: release the capacity
            # the agent's inventory report charged (this controller never
            # dispatched it, so the normal release path can't fire).
            nid, raw = rec
            node = self.nodes.get(nid)
            if node is not None and node.liveness != "DEAD":
                node.available.add(ResourceSet(_raw=raw))
                self._kick()
        info = self.dispatched.pop(task_id, None)
        if info is None and a.get("spec") is None and not a.get("_replayed"):
            # Completion raced ahead of the dispatch reply: park it for
            # _dispatched to replay (with a TTL so duplicates can't leak).
            now = time.monotonic()
            for tid, (_, exp) in list(self.early_done.items()):
                if exp < now:
                    self.early_done.pop(tid, None)
            self.early_done[task_id] = (a, now + 60.0)
            return
        spec: Optional[TaskSpec] = info["spec"] if info else a.get("spec")
        if info is not None and spec.kind != ACTOR_CREATE:
            self._release(info["node_id"], spec, ResourceSet(_raw=spec.resources))
            self._kick()

        if spec is not None and spec.kind == ACTOR_CREATE:
            await self._actor_started(spec, a, info)
            return

        error = a.get("error")
        # Application-level retry: the worker flags user exceptions as
        # retryable when retry_exceptions allows (reference task_manager.cc
        # retries on both system and, when opted-in, application errors).
        if (error is not None and a.get("retryable") and spec is not None
                and spec.attempt < spec.max_retries):
            await self._retry_or_fail(spec, "user exception (retry_exceptions)",
                                      final_error=error)
            return
        for oid, inline, size, holder in a.get("results", []):
            if self._freed(oid):
                await self._purge_late(oid, holder)
                continue
            ent = self.objects.setdefault(oid, _ObjectEntry())
            if ent.state == "ready" and ent.error is None and error is not None:
                # Late/duplicate error report (e.g. a cancel SIGINT landing
                # just after completion): the first good value wins.
                self._notify_owner(ent, oid)
                continue
            if error is not None:
                ent.error = error
            ent.state = "ready"
            ent.inline = inline
            ent.size = size
            if holder is not None:
                ent.holders.add(tuple(holder))
            ent.wake()
            self._notify_owner(ent, oid)

    def _notify_owner(self, ent: _ObjectEntry, oid: str):
        """Queue an object-ready notification for the owner. Notifications
        are flushed as ONE `objects_ready` frame per owner per event-loop
        burst (a batch of task completions costs the owner one frame, not
        one per oid)."""
        owner = ent.owner
        owner_conn = self.client_conns.get(owner)
        if owner_conn is None or owner_conn.closed:
            return
        item = {"oid": oid, "inline": ent.inline,
                "holders": list(ent.holders), "error": ent.error}
        buf = self._ready_bufs.get(owner)
        if buf is not None:
            buf.append(item)  # a flusher for this owner is already running
            return
        self._ready_bufs[owner] = [item]
        asyncio.ensure_future(self._a_flush_ready(owner))

    async def _a_flush_ready(self, owner: str):
        while True:
            items = self._ready_bufs.get(owner)
            if not items:
                self._ready_bufs.pop(owner, None)
                return
            self._ready_bufs[owner] = []
            conn = self.client_conns.get(owner)
            if conn is None or conn.closed:
                self._ready_bufs.pop(owner, None)
                return
            try:
                await conn.push("objects_ready", items=items)
            except Exception:
                self._ready_bufs.pop(owner, None)
                return

    async def _p_task_failed(self, conn, a):
        """Worker/system failure (not a user exception): retry or fail."""
        task_id = a["task_id"]
        info = self.dispatched.pop(task_id, None)
        if info is None:
            return
        spec: TaskSpec = info["spec"]
        if spec.kind != ACTOR_CREATE:
            self._release(info["node_id"], spec, ResourceSet(_raw=spec.resources))
        if self._consume_cancel(task_id) is not None and spec.kind != ACTOR_CREATE:
            await self._finish_cancelled(spec)  # cancelled task must not retry
            self._kick()
            return
        await self._retry_or_fail(spec, a.get("reason", "worker died"))
        self._kick()

    async def _retry_or_fail(self, spec: TaskSpec, reason: str, final_error=None,
                             error_type: str | None = None):
        if spec.kind == ACTOR_CREATE:
            await self._maybe_restart_actor(spec.actor_id, reason)
            return
        if spec.attempt < spec.max_retries:
            spec.attempt += 1
            logger.info("retrying task %s (attempt %d): %s", spec.name, spec.attempt, reason)
            await asyncio.sleep(CONFIG.task_retry_delay_s)
            self.pending.append(spec)
            self._kick()
            return
        if final_error is None:
            from ray_tpu._private.serialization import dumps_oob

            err_header, err_bufs = dumps_oob(
                {"type": error_type or "WorkerCrashedError", "message": reason})
            final_error = [err_header, *err_bufs]
        for oid in spec.return_object_ids():
            if self._freed(oid):
                continue  # owner dropped the ref; don't resurrect the entry
            ent = self.objects.setdefault(oid, _ObjectEntry())
            ent.state = "ready"
            ent.error = final_error
            ent.wake()
            self._notify_owner(ent, oid)

    async def _finish_cancelled(self, spec: TaskSpec):
        from ray_tpu._private.serialization import dumps_oob

        h, b = dumps_oob({"type": "TaskCancelledError", "message": f"task {spec.name} cancelled"})
        for oid in spec.return_object_ids():
            if self._freed(oid):
                continue  # owner dropped the ref; don't resurrect the entry
            ent = self.objects.setdefault(oid, _ObjectEntry())
            ent.state = "ready"
            ent.error = [h, *b]
            ent.wake()
            self._notify_owner(ent, oid)

    async def _h_cancel_task(self, conn, a):
        """Cancel a queued or running task (reference core_worker.proto:492
        CancelTask; force_kill semantics from python/ray/_private/worker.py
        cancel). Queued: removed before dispatch. Running: the node agent
        interrupts (KeyboardInterrupt) or kills (force) the worker."""
        task_id = a["task_id"]
        force = a.get("force", False)
        for spec in list(self.pending):
            if spec.task_id == task_id:
                self.pending.remove(spec)
                await self._finish_cancelled(spec)
                return {"status": "cancelled_pending"}
        info = self.dispatched.get(task_id)
        if info is not None:
            info["spec"].max_retries = 0  # a cancelled task must not retry
            nconn = self.node_conns.get(info["node_id"])
            if nconn is not None and not nconn.closed:
                try:
                    await nconn.push("cancel_task", worker_id=info["worker_id"],
                                     task_id=task_id, force=force)
                except Exception:
                    pass
            return {"status": "cancelling_running"}
        # Not queued and not dispatched: either mid-dispatch or not yet
        # submitted — park the marker; the schedule/dispatch paths consume it.
        now = time.monotonic()
        for tid, (_, exp) in list(self.cancelled.items()):
            if exp < now:
                self.cancelled.pop(tid, None)
        self.cancelled[task_id] = (force, now + 60.0)
        return {"status": "marked"}

    def _consume_cancel(self, task_id: str):
        """Pop a live cancel marker; returns force flag or None."""
        ent = self.cancelled.pop(task_id, None)
        if ent is None:
            return None
        force, exp = ent
        if exp < time.monotonic():
            return None
        return force

    # ------------------------------------------------------------- leases
    async def _h_lease_workers(self, conn, a):
        """Grant up to `count` leased workers matching a resource demand +
        strategy. Each lease holds the demand's resources like a running
        task; the holder streams tasks to the worker directly and returns
        the lease when idle (reference RequestWorkerLease,
        node_manager.proto:404, with the submitter-side lease caching of
        normal_task_submitter.cc)."""
        owner = conn.meta.get("worker_id") or a.get("owner_id")
        demand = ResourceSet(_raw=a["resources"])
        strategy = a["strategy"]
        count = max(1, min(int(a.get("count", 1)), max(1, CONFIG.lease_batch)))
        # Fair share under contention: while other requesters are parked
        # waiting for capacity, one owner must not re-grab the whole pool.
        others = max(0, self._lease_waiters)
        have = int(a.get("have", 0))
        if have > 0 and others > 0:
            # Starving requesters (have=0, parked below) get first claim on
            # freed capacity: a scale-up probe from an owner that already
            # holds leases must not race them for it.
            return {"leases": []}
        granted = await self._grant_leases(
            owner, demand, strategy, max(1, count // (1 + others)))
        if not granted and have > 0:
            # The requester already holds live leases for this class: this
            # is a scale-UP probe, not starvation. Answer "no" immediately —
            # parking it would fire need_resources and steal momentarily-
            # idle leases from owners who are about to reuse them (the
            # redistribution thrash behind the multi-client collapse).
            return {"leases": granted}
        if not granted:
            # Park the request briefly instead of replying empty: ask lease
            # holders for idle returns and retry when capacity frees —
            # client-side polling at REQUEST_RETRY_S granularity convoys
            # concurrent submitters on the idle-return timer (observed 15x
            # multi-client loss). Parked requests are woken by _kick_leases
            # the moment a lease returns; the short wait cap only covers
            # lost wakeups.
            deadline = time.monotonic() + 0.4
            self._lease_waiters += 1
            try:
                while not granted:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    self._maybe_push_need_resources()
                    fut = asyncio.get_running_loop().create_future()
                    self._lease_waiter_futs.append(fut)
                    try:
                        await asyncio.wait_for(fut, min(rem, 0.05))
                    except asyncio.TimeoutError:
                        pass
                    granted = await self._grant_leases(
                        owner, demand, strategy,
                        max(1, count // max(1, self._lease_waiters)))
            finally:
                self._lease_waiters -= 1
        return {"leases": granted}

    async def _grant_leases(self, owner, demand, strategy, count) -> list:
        import copy
        import uuid

        # Placement pass first: pick/consume up to `count` slots (placement
        # authority stays entirely with the scheduler), THEN fill each
        # node's quota — warm pool hits cost no agent round trip, misses
        # ride ONE bulk `lease_workers` call per node.
        by_node: dict[str, list] = {}
        for _ in range(max(1, count)):
            nid = pick_node(demand, strategy, self.nodes, self.pg_bundles)
            if nid is None:
                break
            nconn = self.node_conns.get(nid)
            if nconn is None or nconn.closed:
                break
            # Consume against a per-lease CLONE: _consume_for pins a
            # pg_bundle_index=-1 wildcard to the bundle it consumed, and that
            # pin must not leak into later iterations of this grant loop (or
            # every lease of a multi-count grant collapses onto one bundle's
            # capacity), into the lease entries, or — on the in-process
            # LocalConnection path — into the caller's live strategy object.
            lease_strategy = copy.copy(strategy)
            self._consume_for(nid, lease_strategy, demand)
            by_node.setdefault(nid, []).append(lease_strategy)

        granted = []
        demand_raw = demand.raw()

        def _mint(nid, lease_strategy, worker_id, address, incarnation):
            self.lease_grants += 1
            lease_id = uuid.uuid4().hex[:16]
            addr = tuple(address) if address else None
            self.leases[lease_id] = {
                "owner": owner,
                "node_id": nid,
                "worker_id": worker_id,
                "address": addr,
                "demand": demand_raw,
                "strategy": lease_strategy,
                "incarnation": incarnation,
            }
            granted.append({
                "lease_id": lease_id,
                "node_id": nid,
                "worker_id": worker_id,
                "address": addr,
                "incarnation": incarnation,
            })

        for nid, strategies in by_node.items():
            node = self.nodes[nid]
            rest = []
            for st in strategies:
                pooled = self._pool_pop(nid, demand_raw)
                if pooled is not None:
                    self.lease_pool_hits += 1
                    _mint(nid, st, pooled["worker_id"], pooled["address"],
                          node.incarnation)
                else:
                    rest.append(st)
            if not rest:
                continue
            nconn = self.node_conns.get(nid)
            workers = []
            if nconn is not None and not nconn.closed:
                try:
                    # Margin over the agent's own acquire timeout: if the
                    # agent raises first we get a clean error reply; timing
                    # out here first would strand slots in 'leased' with no
                    # lease entry.
                    rep = await nconn.call(
                        "lease_workers", count=len(rest),
                        resources=demand_raw,
                        _timeout=CONFIG.worker_register_timeout_s + 5)
                    workers = rep.get("workers") or []
                except Exception:
                    workers = []
            # The node may have died/bounced during the agent call: minting
            # a lease against the stale life would leak its accounting.
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                for st in rest:
                    self._release_for(nid, st, demand)
                continue
            for st, w in zip(rest, workers):
                _mint(nid, st, w["worker_id"], w["address"], node.incarnation)
            for st in rest[len(workers):]:
                self._release_for(nid, st, demand)
        return granted

    # -- warm lease pool ---------------------------------------------------
    def _pool_pop(self, nid: str, demand_raw: dict):
        pool = self.lease_pool.get(nid)
        if not pool:
            return None
        now = time.monotonic()
        for i, ent in enumerate(pool):
            if ent["expires"] > now and ent["demand"] == demand_raw:
                self._lease_pool_size -= 1
                return pool.pop(i)
        return None

    def _drop_node_pool(self, nid: str):
        """Forget a node's warm pool (death / reconcile: the slots are
        gone, or the inventory sweep will unlease them)."""
        dropped = self.lease_pool.pop(nid, None)
        if dropped:
            self._lease_pool_size -= len(dropped)

    async def _unlease(self, nid: str, worker_id: str):
        nconn = self.node_conns.get(nid)
        if nconn is not None and not nconn.closed:
            try:
                await nconn.push("unlease_worker", worker_id=worker_id)
            except Exception:
                pass

    async def _sweep_lease_pool(self):
        """Expire warm pool entries (runs from the health loop): the agent
        finally gets its worker slot back. ALL pool mutation happens before
        the first await — writing a pre-await snapshot back would resurrect
        entries popped by a concurrent grant (double-granting one worker
        slot) and drop entries returned during the await."""
        now = time.monotonic()
        to_unlease = []
        for nid in list(self.lease_pool):
            pool = self.lease_pool[nid]
            keep = [e for e in pool if e["expires"] > now]
            expired = [e for e in pool if e["expires"] <= now]
            if not expired:
                continue
            self._lease_pool_size -= len(expired)
            if keep:
                self.lease_pool[nid] = keep
            else:
                self.lease_pool.pop(nid, None)
            to_unlease.extend((nid, e["worker_id"]) for e in expired)
        for nid, wid in to_unlease:
            await self._unlease(nid, wid)

    def _consume_for(self, nid: str, strategy, demand: ResourceSet):
        if strategy.kind == "PLACEMENT_GROUP":
            for (pgid, idx), b in self.pg_bundles.items():
                if pgid == strategy.pg_id and b["node"] == nid and b["available"].fits(demand):
                    if strategy.pg_bundle_index in (-1, idx):
                        b["available"].subtract(demand)
                        strategy.pg_bundle_index = idx
                        return
        self.nodes[nid].available.subtract(demand)

    def _release_for(self, nid: str, strategy, demand: ResourceSet):
        if strategy.kind == "PLACEMENT_GROUP":
            b = self.pg_bundles.get((strategy.pg_id, strategy.pg_bundle_index))
            if b is not None:
                b["available"].add(demand)
                return
        node = self.nodes.get(nid)
        # SUSPECT nodes still take releases: their accounting is frozen, not
        # discarded, and must be correct if the agent reconnects in time.
        if node is not None and node.liveness != "DEAD":
            node.available.add(demand)

    def _drop_lease(self, lease_id: str, release: bool = True):
        ent = self.leases.pop(lease_id, None)
        if ent is None:
            return None
        if release:
            self._release_for(ent["node_id"], ent["strategy"], ResourceSet(_raw=ent["demand"]))
            self._kick()
        return ent

    async def _h_return_leases(self, conn, a):
        keep = CONFIG.lease_idle_s
        now = time.monotonic()
        for lease_id in a["lease_ids"]:
            ent = self._drop_lease(lease_id)
            if ent is None:
                continue
            self.lease_returns += 1
            nid = ent["node_id"]
            node = self.nodes.get(nid)
            # Keep the returned worker warm: the slot stays 'leased' at the
            # agent and a matching regrant within the idle window skips the
            # whole agent round trip (multi-client handoff hot path).
            if (keep > 0 and node is not None and node.alive
                    and node.incarnation == ent.get("incarnation",
                                                    node.incarnation)
                    and self._lease_pool_size < 256):
                self.lease_pool.setdefault(nid, []).append({
                    "worker_id": ent["worker_id"],
                    "address": ent.get("address"),
                    "demand": ent["demand"],
                    "expires": now + keep,
                })
                self._lease_pool_size += 1
                continue
            await self._unlease(nid, ent["worker_id"])
        return {}

    async def _h_kill_leased_worker(self, conn, a):
        """Force-cancel support for the direct task path: kill the worker
        process behind a lease (the holder fails its in-flight tasks when the
        direct connection drops). The lease is dropped HERE: the agent's
        kill_worker marks the slot dead before exit, so no worker_died report
        will follow to release the resources."""
        for lease_id, ent in list(self.leases.items()):
            if ent["worker_id"] == a["worker_id"]:
                # Only claim the kill once the push to the node agent was
                # actually sent: the caller un-dooms the lease on killed=False
                # and would otherwise wait forever for a death that is never
                # coming (the lease must also survive here in that case).
                nconn = self.node_conns.get(ent["node_id"])
                if nconn is None or nconn.closed:
                    return {"killed": False}
                try:
                    await nconn.push("kill_worker", worker_id=ent["worker_id"])
                except Exception:
                    return {"killed": False}
                self._drop_lease(lease_id)
                return {"killed": True}
        return {"killed": False}

    async def _reap_owner_leases(self, owner: str):
        """A lease holder disconnected: give its workers back to the pools."""
        for lease_id, ent in list(self.leases.items()):
            if ent["owner"] != owner:
                continue
            self._drop_lease(lease_id)
            nconn = self.node_conns.get(ent["node_id"])
            if nconn is not None and not nconn.closed:
                try:
                    await nconn.push("unlease_worker", worker_id=ent["worker_id"])
                except Exception:
                    pass

    async def _lease_worker_died(self, worker_id: str, cause: str | None = None):
        from ray_tpu._private import events as _events

        for lease_id, ent in list(self.leases.items()):
            if ent["worker_id"] == worker_id:
                self._drop_lease(lease_id)
                # One normalized cause vocabulary end to end: the lease
                # holder's failure messages key off it ("oom"/"stall"),
                # and `ray-tpu events` queries by cause actually match.
                norm = _events.normalize_exit_cause(cause)
                self._emit_event(
                    "lease_failover",
                    f"lease {lease_id[:8]} invalidated: worker "
                    f"{worker_id[:12]} died ({norm}); in-flight specs fail "
                    f"over", entity=(lease_id, worker_id, ent["owner"]),
                    node_id=ent.get("node_id"), attrs={"cause": norm})
                oconn = self.client_conns.get(ent["owner"])
                if oconn is not None and not oconn.closed:
                    try:
                        await oconn.push("lease_invalid", lease_id=lease_id,
                                         cause=norm)
                    except Exception:
                        pass
        # A pooled (returned-but-warm) worker dying must leave the pool, or
        # a later grant would hand out a corpse.
        for nid, pool in list(self.lease_pool.items()):
            alive = [e for e in pool if e["worker_id"] != worker_id]
            if len(alive) != len(pool):
                self._lease_pool_size -= len(pool) - len(alive)
                if alive:
                    self.lease_pool[nid] = alive
                else:
                    self.lease_pool.pop(nid, None)

    def _maybe_push_need_resources(self):
        """Demand exists that can't place while clients hold leases: ask them
        to give idle ones back (rate-limited)."""
        if not self.leases:
            return
        now = time.monotonic()
        # 20ms floor: a parked lease request's unblock chain is need-push ->
        # owner idle-return -> regrant, so this throttle sits directly on
        # multi-client handoff latency.
        if now - self._last_need_push < 0.02:
            return
        self._last_need_push = now
        owners = {ent["owner"] for ent in self.leases.values()}
        for owner in owners:
            oconn = self.client_conns.get(owner)
            if oconn is not None and not oconn.closed:
                try:
                    oconn.push_threadsafe("need_resources")
                except Exception:
                    pass

    # ------------------------------------------------------------- objects
    async def _h_register_put(self, conn, a):
        if self._freed(a["oid"]):
            await self._purge_late(
                a["oid"], a.get("holder"),
                device_worker=(a.get("device_worker")
                               if a.get("plane") == "device" else None))
            return {}
        ent = self.objects.setdefault(a["oid"], _ObjectEntry())
        ent.state = "ready"
        ent.owner = a.get("owner") or conn.meta.get("worker_id")
        ent.size = a["size"]
        if a.get("plane"):
            ent.plane = a["plane"]
            ent.device_worker = a.get("device_worker")
            ent.device_node = a.get("device_node")
            if ent.device_worker:
                self._device_index.setdefault(
                    ent.device_worker, set()).add(a["oid"])
        if a.get("inline") is not None:
            ent.inline = a["inline"]
        if a.get("holder") is not None:
            ent.holders.add(tuple(a["holder"]))
        if a.get("error") is not None:
            ent.error = a["error"]
        ent.wake()
        return {}

    async def _p_register_put(self, conn, a):
        """Push variant (no ack) — used by actor workers to advertise call
        results without adding a round trip to the direct-call fast path."""
        await self._h_register_put(conn, a)

    async def _p_register_puts(self, conn, a):
        """Batched advertise: one frame per flush of a worker's direct-path
        result flusher."""
        for item in a["items"]:
            await self._h_register_put(conn, item)

    async def _p_add_location(self, conn, a):
        ent = self.objects.get(a["oid"])
        if ent is not None:
            ent.holders.add(tuple(a["holder"]))

    async def _h_wait_object(self, conn, a):
        oid = a["oid"]
        timeout = a.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if self._freed(oid):
                # Owner already dropped its last reference: fail fast
                # instead of resurrecting a permanently-pending entry.
                return {"status": "lost"}
            ent = self.objects.setdefault(oid, _ObjectEntry())
            if ent.state == "ready":
                return {
                    "status": "ready",
                    "inline": ent.inline,
                    "holders": list(ent.holders),
                    "error": ent.error,
                }
            if ent.state == "lost":
                return {"status": "lost"}
            fut = asyncio.get_running_loop().create_future()
            ent.waiters.append(fut)
            try:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return {"status": "timeout"}

    # --------------------------------------------------------------- jobs
    async def _h_submit_job(self, conn, a):
        """Run an entrypoint shell command as a driver subprocess on a node
        agent (reference JobManager.submit_job,
        dashboard/modules/job/job_manager.py:423)."""
        sid = a.get("submission_id") or f"raysubmit_{os.urandom(8).hex()}"
        if sid in self.jobs and self.jobs[sid]["status"] in ("PENDING", "RUNNING"):
            raise rpc.RpcError(f"job {sid} already exists")
        nid, nconn = None, None
        for cand, c in self.node_conns.items():
            if not c.closed and self.nodes.get(cand) and self.nodes[cand].alive:
                nid, nconn = cand, c
                break
        if nconn is None:
            raise rpc.RpcError("no alive node to run the job on")
        self.jobs[sid] = {
            "submission_id": sid, "entrypoint": a["entrypoint"],
            "status": "PENDING", "message": "", "node_id": nid,
            "start_time": time.time(), "end_time": None,
            "metadata": a.get("metadata") or {},
            "runtime_env": a.get("runtime_env") or {},
        }
        try:
            rep = await nconn.call(
                "run_job", submission_id=sid, entrypoint=a["entrypoint"],
                runtime_env=a.get("runtime_env"))
        except Exception as e:
            # The RPC failing must not strand the id in PENDING forever
            # (non-terminal states block resubmission of the same id).
            job = self.jobs[sid]
            job["status"] = "FAILED"
            job["message"] = f"run_job RPC failed: {e!r}"
            job["end_time"] = time.time()
            raise
        job = self.jobs[sid]
        if rep.get("status") == "running":
            job["status"] = "RUNNING"
        else:
            job["status"] = "FAILED"
            job["message"] = rep.get("message", "spawn failed")
            job["end_time"] = time.time()
        self._emit_event(
            "job_start",
            f"job {sid} ({a['entrypoint']!r}) -> {job['status']}",
            entity=(sid,), node_id=nid, attrs={"status": job["status"]})
        return {"submission_id": sid, "status": job["status"]}

    async def _p_job_done(self, conn, a):
        if conn is not None and conn.meta.get("kind") == "node" \
                and self._fenced_node(conn, a) is None:
            return  # stale-incarnation zombie
        job = self.jobs.get(a["submission_id"])
        if job is None or job["status"] not in ("PENDING", "RUNNING"):
            return
        rc = a.get("returncode")
        if a.get("stopped"):
            job["status"] = "STOPPED"
        elif rc == 0:
            job["status"] = "SUCCEEDED"
        else:
            job["status"] = "FAILED"
            job["message"] = f"entrypoint exited with code {rc}"
        job["end_time"] = time.time()
        self._emit_event(
            "job_stop",
            f"job {job['submission_id']} -> {job['status']}"
            + (f" ({job['message']})" if job.get("message") else ""),
            severity=("warning" if job["status"] == "FAILED" else "info"),
            entity=(job["submission_id"],), node_id=a.get("node_id"),
            attrs={"status": job["status"], "returncode": rc})
        self._publish("job", {"submission_id": job["submission_id"],
                              "status": job["status"]})

    async def _h_stop_job(self, conn, a):
        sid = a["submission_id"]
        job = self.jobs.get(sid)
        if job is None:
            raise rpc.RpcError(f"job {sid} not found")
        if job["status"] not in ("PENDING", "RUNNING"):
            return {"stopped": False, "status": job["status"]}
        nconn = self.node_conns.get(job["node_id"])
        if nconn is None or nconn.closed:
            job["status"] = "FAILED"
            job["message"] = "job node died"
            job["end_time"] = time.time()
            return {"stopped": False, "status": job["status"]}
        rep = await nconn.call("stop_job", submission_id=sid)
        return {"stopped": rep.get("stopped", False), "status": job["status"]}

    async def _h_get_job(self, conn, a):
        job = self.jobs.get(a["submission_id"])
        if job is None:
            raise rpc.RpcError(f"job {a['submission_id']} not found")
        return {"job": job}

    async def _h_list_jobs(self, conn, a):
        return {"jobs": list(self.jobs.values())}

    async def _h_job_logs(self, conn, a):
        sid = a["submission_id"]
        job = self.jobs.get(sid)
        if job is None:
            raise rpc.RpcError(f"job {sid} not found")
        nconn = self.node_conns.get(job["node_id"])
        if nconn is None or nconn.closed:
            return {"data": b"", "offset": int(a.get("offset", 0)),
                    "found": False, "truncated": False}
        return await nconn.call("job_logs", submission_id=sid,
                                offset=int(a.get("offset", 0)))

    # -------------------------------------------------------- observability
    async def _p_metrics_report(self, conn, a):
        """Aggregate application metric records (reference: workers export
        through the metrics agent to Prometheus; here the controller is the
        aggregation point, stats/metric.h role). Tracing spans piggyback on
        the same frames (`spans` key) — see _ingest_spans."""
        for rec in a["records"]:
            kind = rec["kind"]
            if kind == "histogram_decl":
                # Boundaries registered once per (name, boundaries) by the
                # first observe in each process; value records then ride
                # bare. Idempotent: duplicate decls (per-process, races)
                # simply rewrite the same list.
                self._hist_bounds[rec["name"]] = list(rec["boundaries"])
                # Self-heal series that aggregated DEGRADED (one +Inf
                # bucket) before their decl arrived — e.g. a decl lost to a
                # dropped batch, re-sent after the worker reconnected. Past
                # observations keep count/sum; bucketing starts now.
                for ent in self.metrics.values():
                    if (ent["name"] == rec["name"]
                            and ent.get("buckets") is not None
                            and not ent.get("boundaries")):
                        ent["boundaries"] = list(rec["boundaries"])
                        ent["buckets"] = [0] * (len(rec["boundaries"]) + 1)
                continue
            key = (rec["name"], tuple(sorted(rec["tags"].items())))
            ent = self.metrics.get(key)
            if ent is None:
                ent = self.metrics[key] = {
                    "name": rec["name"], "kind": rec["kind"],
                    "desc": rec.get("desc", ""), "tags": rec["tags"],
                    "value": 0.0, "count": 0, "sum": 0.0, "buckets": None,
                }
            if kind == "counter":
                ent["value"] += rec["value"]
            elif kind == "gauge":
                ent["value"] = rec["value"]
            elif kind == "histogram":
                if ent["buckets"] is None:
                    # Boundaries from the decl registry; legacy records
                    # carrying them inline still work. A decl lost to a
                    # controller restart degrades to count/sum only (one
                    # +Inf bucket) instead of dropping observations.
                    bounds = (rec.get("boundaries")
                              or self._hist_bounds.get(rec["name"]) or [])
                    ent["boundaries"] = list(bounds)
                    ent["buckets"] = [0] * (len(bounds) + 1)
                import bisect

                ent["buckets"][bisect.bisect_left(ent["boundaries"], rec["value"])] += 1
                ent["count"] += 1
                ent["sum"] += rec["value"]
        spans = a.get("spans")
        if spans:
            self._ingest_spans(spans)
        evs = a.get("events")
        if evs:
            self._ingest_events(evs)

    async def _h_get_metrics(self, conn, a):
        # Aggregated application series PLUS the controller's
        # self-telemetry, synthesized at scrape time (no tick needed):
        # per-RPC-method latency histograms, table-size gauges, and — when
        # the sampling plane is armed — the event-loop lag gauge. All of
        # it flows into the dashboard's /metrics Prometheus exposition.
        out = list(self.metrics.values())
        for method, (n, s, buckets) in sorted(self._rpc_stats.items()):
            out.append({
                "name": "rt_controller_rpc_seconds", "kind": "histogram",
                "desc": "controller RPC handler latency by method",
                "tags": {"method": method}, "value": 0.0, "count": n,
                "sum": round(s, 6), "boundaries": list(_RPC_BOUNDS),
                "buckets": list(buckets)})
        for table, size in self._table_sizes().items():
            out.append({
                "name": "rt_controller_table_size", "kind": "gauge",
                "desc": "controller state-table row counts",
                "tags": {"table": table}, "value": float(size),
                "count": 0, "sum": 0.0, "buckets": None})
        if self._loop_lag is not None:
            out.append({
                "name": "rt_controller_loop_lag_seconds", "kind": "gauge",
                "desc": "controller event-loop scheduling lag",
                "tags": {}, "value": float(self._loop_lag),
                "count": 0, "sum": 0.0, "buckets": None})
        return {"metrics": out}

    # ------------------------------------------------------ telemetry plane
    def _table_sizes(self) -> dict:
        """Row counts of the controller's hot tables — the direct input to
        ROADMAP item 3's control-plane scale work (which tables grow is
        which tables shard first)."""
        return {
            "objects": len(self.objects),
            "actors": len(self.actors),
            "leases": len(self.leases),
            "parked_grants": self._lease_waiters,
            "pending_tasks": len(self.pending),
            "dispatched_tasks": len(self.dispatched),
            "nodes": len(self.nodes),
            "clients": len(self.client_conns),
            "kv": len(self.kv),
            "traces": len(self.traces),
            "events": len(self.events),
        }

    def _telem_append(self, key: tuple, ts: float, val) -> None:
        if not isinstance(val, (int, float)):
            return
        points = max(16, int(CONFIG.telemetry_points))
        ring = self.telemetry.get(key)
        if ring is None:
            ring = self.telemetry[key] = _SeriesRing(points)
        ring.append(ts, val, points)

    #: Agent wall clocks further than this from the controller's are
    #: rebased at ingest: window pruning, since= filtering, and sample_age
    #: all compare against the CONTROLLER clock, and an unsynced node
    #: would otherwise have its series pruned on arrival (clock behind) or
    #: kept past age-out (clock ahead). Small skew passes through — the
    #: 600s window and 120s sparkline dwarf it.
    _TELEM_SKEW_REBASE_S = 30.0

    def _ingest_telemetry(self, nid: str, batches: list) -> None:
        """Fold heartbeat-piggybacked sample batches into the per-(node,
        series) rings. Worker-scoped series key on a 12-char worker-id
        prefix (matches every other surface's display ids)."""
        tss = []
        for b in batches:
            try:
                tss.append(float(b.get("ts") or time.time()))
            except (TypeError, ValueError):
                tss.append(None)
        newest = max((t for t in tss if t is not None), default=None)
        # Delivery just happened, so the newest batch was sampled within
        # ~one heartbeat of controller-now: a larger gap is clock skew.
        # The applied offset is STICKY per node (re-locked only when the
        # measured skew moves a full threshold away from it): a hard
        # threshold alone would flip offset on/off for skew hovering near
        # it, and the ring's monotone guard would then reject alternate
        # deliveries wholesale.
        offset = self._telem_skew.get(nid, 0.0)
        if newest is not None:
            skew = time.time() - newest
            if abs(skew - offset) > self._TELEM_SKEW_REBASE_S:
                offset = skew if abs(skew) > self._TELEM_SKEW_REBASE_S \
                    else 0.0
                self._telem_skew[nid] = offset
        for b, ts in zip(batches, tss):
            if ts is None:
                continue
            ts += offset
            for series, val in (b.get("node") or {}).items():
                self._telem_append((nid, f"node.{series}", ""), ts, val)
            for wid, wseries in (b.get("workers") or {}).items():
                sub = str(wid)[:12]
                for series, val in (wseries or {}).items():
                    # Dotted keys are already fully-qualified series names
                    # (e.g. the engine's `llm.tokens_per_s`); bare keys
                    # get the worker. family prefix.
                    name = series if "." in series else f"worker.{series}"
                    self._telem_append((nid, name, sub), ts, val)
        self._telem_prune()

    def _telem_prune(self) -> None:
        """Age out series with no fresh point for RT_TELEMETRY_WINDOW_S (a
        dead agent or reaped worker leaves no stuck series). Rate-limited:
        one sweep per ~window/8."""
        window = max(5.0, float(CONFIG.telemetry_window_s))
        now = time.time()
        if now < self._telem_prune_at:
            return
        self._telem_prune_at = now + max(1.0, window / 8.0)
        cutoff = now - window
        for key in [k for k, r in self.telemetry.items()
                    if r.last_ts < cutoff]:
            self.telemetry.pop(key, None)
        live_nodes = {k[0] for k in self.telemetry}
        for nid in [n for n in self._telem_skew if n not in live_nodes]:
            self._telem_skew.pop(nid, None)

    def _telem_purge_worker(self, worker_id: str) -> None:
        """Drop a dead worker's per-worker series immediately: its rings
        would otherwise keep reporting the last HBM/compile/RSS sample as
        current via cluster_utilization/`ray-tpu top` until the
        RT_TELEMETRY_WINDOW_S prune — the freezing-last-values failure
        mode the node-death path already avoids."""
        sub = str(worker_id)[:12]
        for key in [k for k in self.telemetry if k[2] == sub]:
            self.telemetry.pop(key, None)

    async def _self_sample_loop(self):
        """Controller self-telemetry tick (armed with the sampling plane):
        measures event-loop scheduling lag and feeds the controller's own
        table sizes into the same ring the node series live in, under the
        reserved node id "controller"."""
        from ray_tpu._private import telemetry as _telemetry

        interval = max(0.05, _telemetry.interval_s())
        while not self._stopping:
            t0 = time.monotonic()
            await asyncio.sleep(interval)
            lag = max(0.0, time.monotonic() - t0 - interval)
            self._loop_lag = round(lag, 6)
            ts = time.time()
            self._telem_append(("controller", "ctrl.loop_lag_s", ""),
                               ts, self._loop_lag)
            for table, size in self._table_sizes().items():
                self._telem_append(("controller", f"ctrl.{table}", ""),
                                   ts, size)
            self._telem_prune()

    async def _h_timeseries(self, conn, a):
        """Query the telemetry rings: /api/timeseries?series=&node_id=&since=
        and `util.state.timeseries()`. `series` matches exactly or as a
        prefix (`node.` selects the whole family); points are
        [[ts, value], ...], timestamps strictly monotone per row."""
        sel = a.get("series") or None
        nid = a.get("node_id") or None
        since = a.get("since")
        since = float(since) if since is not None else None
        self._telem_prune()
        rows = []
        for (knid, series, sub), ring in self.telemetry.items():
            if nid is not None and knid != nid:
                continue
            if sel is not None and series != sel \
                    and not series.startswith(sel):
                continue
            pts = ring.points(since)
            if not pts:
                continue
            rows.append({"node_id": knid, "series": series,
                         "worker_id": sub or None, "points": pts})
        rows.sort(key=lambda r: (r["node_id"], r["series"],
                                 r["worker_id"] or ""))
        return {"series": rows, "now": time.time(),
                "interval_s": CONFIG.telemetry_interval_s,
                "window_s": CONFIG.telemetry_window_s}

    async def _h_cluster_utilization(self, conn, a):
        """Latest sample per node/worker plus controller self-stats — the
        one-call backing of `ray-tpu top` and
        `util.state.cluster_utilization()`."""
        self._telem_prune()
        nodes: dict[str, dict] = {}
        for nid, n in self.nodes.items():
            nodes[nid] = {
                "alive": n.alive, "liveness": n.liveness,
                "beat_age": round(time.monotonic() - n.last_beat, 3),
                "node": {}, "workers": {},
            }
        for (knid, series, sub), ring in self.telemetry.items():
            last = ring.latest()
            if last is None or knid == "controller":
                continue
            ent = nodes.get(knid)
            if ent is None:  # series outliving its node entry (death race)
                continue
            if sub:
                # worker.-family series drop the prefix ("worker.cpu" ->
                # "cpu"); fully-qualified dotted series (the engine's
                # "llm.tokens_per_s") keep their name — `ray-tpu top`
                # reads them by it.
                key = (series.split(".", 1)[1]
                       if series.startswith("worker.") else series)
                ent["workers"].setdefault(sub, {})[key] = last[1]
            else:
                ent["node"][series.split(".", 1)[1]] = last[1]
            age = round(time.time() - ring.last_ts, 3)
            if "sample_age" not in ent or age < ent["sample_age"]:
                ent["sample_age"] = age  # freshest series wins
        # Serve-plane summary (README "Cross-host streaming & multi-proxy"):
        # per-proxy request/stream tallies plus the push-stream transport
        # counters, scraped from the aggregated application metrics so
        # `ray-tpu top` shows the ingress fleet without a second RPC.
        proxies: dict[str, dict] = {}
        stream = {"records": 0, "bytes": 0, "parks": 0}
        for ent in self.metrics.values():
            name = ent["name"]
            if name.startswith("rt_serve_proxy_"):
                pid = ent["tags"].get("proxy", "?")
                row = proxies.setdefault(
                    pid, {"requests": 0, "streams": 0, "active": 0})
                if name == "rt_serve_proxy_requests_total":
                    row["requests"] = int(ent["value"])
                elif name == "rt_serve_proxy_streams_total":
                    row["streams"] = int(ent["value"])
                elif name == "rt_serve_proxy_active_streams":
                    row["active"] = int(ent["value"])
            elif name == "rt_stream_push_records_total":
                stream["records"] = int(ent["value"])
            elif name == "rt_stream_push_bytes_total":
                stream["bytes"] = int(ent["value"])
            elif name == "rt_stream_push_parks_total":
                stream["parks"] = int(ent["value"])
        return {
            "nodes": nodes,
            "controller": {
                "loop_lag_s": self._loop_lag,
                "tables": self._table_sizes(),
                "rpc_total": sum(v[0] for v in self._rpc_stats.values()),
            },
            "serve": {"proxies": proxies, "stream": stream},
            "telemetry_armed": bool(self.telemetry) or
                self._telem_task is not None,
            "now": time.time(),
        }

    # ----------------------------------------------------- worker profiling
    async def _h_profile_worker(self, conn, a):
        """Route an on-demand profile capture to the agent hosting the
        worker (same lookup as worker_stacks), then register the returned
        metadata in the KV (`_profiles` namespace) so list_profiles rows
        survive the capture path."""
        from ray_tpu._private import telemetry as _telemetry

        wid = a.get("worker_id") or ""
        nid = a.get("node_id")
        if nid is None:
            hits = self._find_worker_nodes(wid)
            if len(hits) > 1:
                return {"found": False,
                        "error": f"worker id prefix {wid[:12]!r} is "
                                 f"ambiguous ({len(hits)} nodes match) — "
                                 f"use a longer prefix"}
            nid = next(iter(hits)) if hits else None
        if nid is None:
            return {"found": False,
                    "error": f"worker {wid[:12]} not found in the actor, "
                             f"lease, or dispatch tables (pass node_id, or "
                             f"profile while it is running work)"}
        nconn = self.node_conns.get(nid)
        if nconn is None or nconn.closed:
            return {"found": False, "error": f"node {nid[:8]} not connected"}
        seconds = _telemetry.clamp_profile_seconds(a.get("seconds"))
        try:
            rep = await nconn.call(
                "profile_worker", worker_id=wid, seconds=seconds,
                mode=a.get("mode") or "cpu", hz=a.get("hz"),
                _timeout=seconds + 40.0)
        except Exception as e:
            # Agent death/sever/timeout mid-capture follows the same
            # attributed-error contract as every other failure branch
            # here. A persist that merely outlived the timeout still
            # registers via the agent's profile_persisted push.
            return {"found": False,
                    "error": f"profile via node {nid[:8]} failed "
                             f"mid-capture ({type(e).__name__}: {e})"}
        if rep.get("found") and rep.get("profile"):
            # Idempotent with the agent's profile_persisted push (the
            # authoritative registration — it lands even when a slow
            # storage persist outlives this call's timeout budget); kept
            # here as backup for a push lost to a reconnecting conn.
            self._register_profile(rep["profile"])
        return rep

    async def _p_profile_persisted(self, conn, a):
        """Agent push after a captured profile lands in the storage plane.
        Registration rides this push rather than only the profile_worker
        reply so a persist slower than the caller's RPC timeout still
        indexes the document it wrote (orphaned docs are invisible to
        list_profiles/get_profile forever)."""
        meta = a.get("profile")
        if isinstance(meta, dict) and meta.get("name"):
            self._register_profile(meta)

    def _register_profile(self, meta: dict) -> None:
        import json as _json

        self.kv[("_profiles", meta["name"])] = _json.dumps(
            meta, default=str).encode()
        # Bounded registry (ring discipline, like traces/stalls):
        # automated periodic profiling must not grow the KV — and
        # every controller snapshot — forever. Evicted rows lose only
        # their index entry; the documents stay in the storage plane.
        names = sorted(k[1] for k in self.kv
                       if k[0] == "_profiles")
        for stale in names[:-self._PROFILE_INDEX_CAP]:
            self.kv.pop(("_profiles", stale), None)
        self._mark_dirty()

    _PROFILE_INDEX_CAP = 512  # metadata rows kept (oldest evicted)

    def _find_worker_nodes(self, wid: str) -> set[str]:
        """Nodes hosting workers matching `wid` (exact id or prefix), from
        the actor / lease / dispatch tables. One hit routes; zero and
        many are distinct error cases (missing vs ambiguous prefix)."""
        hits: set[str] = set()
        for ent in self.actors.values():
            if ent.worker_id and ent.worker_id.startswith(wid):
                hits.add(ent.node_id)
        for lease in self.leases.values():
            if str(lease.get("worker_id") or "").startswith(wid):
                hits.add(lease["node_id"])
        for info in self.dispatched.values():
            if str(info.get("worker_id") or "").startswith(wid):
                hits.add(info["node_id"])
        hits.discard(None)
        return hits

    async def _h_list_profiles(self, conn, a):
        """Captured-profile metadata rows from the KV registry, newest
        last; same limit/truncation contract as the other list APIs."""
        import json as _json

        limit = int(a.get("limit", 1000))
        rows = []
        for (ns, name), blob in self.kv.items():
            if ns != "_profiles":
                continue
            try:
                rows.append(_json.loads(blob))
            except ValueError:
                continue
        rows.sort(key=lambda r: r.get("created") or 0)
        truncated = len(rows) > limit
        return {"profiles": rows[-limit:], "truncated": truncated}

    async def _h_get_profile(self, conn, a):
        """Fetch one persisted profile document by name (unique prefixes
        accepted) from the storage plane."""
        import json as _json

        name = a.get("name") or ""
        metas = []
        for (ns, key), blob in self.kv.items():
            if ns == "_profiles" and key.startswith(name):
                metas.append(blob)
        if len(metas) != 1:
            return {"found": False, "name": name,
                    "error": ("no profile matches" if not metas
                              else "ambiguous prefix")}
        meta = _json.loads(metas[0])

        def _load(path=meta.get("path")):
            # Read AND parse off the event loop: a cpu capture's document
            # (thousands of traceEvents) is easily multi-MB of JSON.
            from ray_tpu import storage

            return _json.loads(storage.get_bytes(path))

        try:
            doc = await asyncio.get_running_loop().run_in_executor(
                None, _load)
        except Exception as e:
            return {"found": False, "name": name,
                    "error": f"profile doc unreadable: {e!r}"}
        return {"found": True, **doc}

    # ------------------------------------------------------- tracing plane
    _TRACE_SPAN_CAP = 8192  # spans kept per trace (ring discipline)

    def _ingest_spans(self, spans: list) -> None:
        """Index worker-drained spans per trace_id (README "Tracing &
        timeline"). The index is a bounded arrival-order ring: past
        RT_TRACE_MAX_TRACES the oldest trace is evicted (persisted first if
        it never was). A span with no parent is the trace ROOT — its
        arrival marks the trace complete."""
        cap = max(1, int(CONFIG.trace_max_traces))
        now = time.time()
        for sp in spans:
            tid = sp.get("t")
            if not tid:
                continue
            ent = self.traces.get(tid)
            if ent is None:
                while len(self.traces) >= cap:
                    old_tid = next(iter(self.traces))
                    old = self.traces.pop(old_tid)
                    if old.get("dirty"):
                        self._evicted_traces.append((old_tid, old))
                ent = self.traces[tid] = {
                    "spans": [], "start": sp.get("a", now), "last": 0.0,
                    "name": None, "root_done": False, "dirty": False,
                    "recv": now,
                }
            if len(ent["spans"]) < self._TRACE_SPAN_CAP:
                ent["spans"].append(sp)
            ent["start"] = min(ent["start"], sp.get("a", now))
            ent["last"] = max(ent["last"], sp.get("b", now))
            ent["dirty"] = True
            ent["recv"] = now
            if sp.get("p") is None:
                ent["root_done"] = True
                ent["name"] = sp.get("n")
            elif ent["name"] is None:
                ent["name"] = sp.get("n")
        if self._trace_sweep_task is None and not self._stopping:
            self._trace_sweep_task = asyncio.ensure_future(
                self._trace_sweep())
            self._tasks.append(self._trace_sweep_task)

    def _trace_dir(self) -> str | None:
        d = CONFIG.trace_dir
        if d == "none":
            return None
        if d:
            return d
        return os.path.join(CONFIG.session_dir, self.session_id, "traces")

    async def _trace_sweep(self):
        """Persist settled traces through the storage plane (PR 8), batched
        and OFF the event loop: every ~2s, traces quiet for 2s with new
        spans since their last write — plus a bounded batch of evicted
        traces — go out as one executor job. Settled re-dirtied traces (a
        late straggler span) re-persist next sweep."""
        while not self._stopping:
            await asyncio.sleep(2.0)
            try:
                d = self._trace_dir()
                if d is None:
                    self._evicted_traces.clear()
                    continue
                now = time.time()
                batch = []
                while self._evicted_traces and len(batch) < 128:
                    tid, ent = self._evicted_traces.popleft()
                    batch.append((tid, self._trace_doc(tid, ent)))
                for tid, ent in self.traces.items():
                    if ent["dirty"] and now - ent["recv"] >= 2.0:
                        ent["dirty"] = False
                        batch.append((tid, self._trace_doc(tid, ent)))
                if batch:
                    loop = asyncio.get_running_loop()
                    await loop.run_in_executor(
                        None, self._persist_traces_sync, d, batch)
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad tick (an executor mid-shutdown, a storage blip)
                # must not end persistence for the controller's lifetime —
                # the sweep-task sentinel is never reset, so a dead sweep
                # would silently stop all trace persistence.
                logger.exception("trace persistence sweep tick failed; "
                                 "retrying")

    @staticmethod
    def _trace_doc(tid: str, ent: dict) -> dict:
        return {"trace_id": tid, "name": ent.get("name"),
                "start": ent.get("start"), "end": ent.get("last"),
                "complete": bool(ent.get("root_done")),
                "spans": list(ent["spans"])}

    @staticmethod
    def _persist_traces_sync(trace_dir: str, batch: list) -> None:
        import json

        from ray_tpu import storage

        for tid, doc in batch:
            try:
                storage.put(storage.join(trace_dir, f"{tid}.json"),
                            json.dumps(doc).encode())
            except Exception:
                logger.debug("trace persist failed for %s", tid,
                             exc_info=True)

    async def _h_list_traces(self, conn, a):
        limit = int(a.get("limit", 1000))
        rows = []
        for tid, ent in self.traces.items():
            rows.append({"trace_id": tid, "name": ent.get("name"),
                         "start": ent.get("start"), "end": ent.get("last"),
                         "spans": len(ent["spans"]),
                         "complete": bool(ent.get("root_done"))})
        return {"traces": rows[-limit:], "truncated": len(rows) > limit}

    async def _h_get_trace(self, conn, a):
        """Spans of one trace; unique id prefixes accepted (CLI ergonomics).
        Falls back to the storage plane for traces evicted from the ring."""
        tid = a["trace_id"]
        ent = self.traces.get(tid)
        if ent is None:
            matches = [t for t in self.traces if t.startswith(tid)]
            if len(matches) == 1:
                tid, ent = matches[0], self.traces[matches[0]]
        if ent is not None:
            return {"found": True, **self._trace_doc(tid, ent)}
        d = self._trace_dir()
        if d is not None:
            loop = asyncio.get_running_loop()
            doc = await loop.run_in_executor(
                None, self._load_trace_sync, d, tid)
            if doc is not None:
                return {"found": True, **doc}
        return {"found": False, "trace_id": tid, "spans": []}

    @staticmethod
    def _load_trace_sync(trace_dir: str, tid: str):
        import json

        from ray_tpu import storage

        try:
            return json.loads(
                storage.get_bytes(storage.join(trace_dir, f"{tid}.json")))
        except Exception:
            pass
        # Unique-PREFIX lookup over persisted ids: `ray-tpu stalls` prints
        # 12-char trace prefixes, and an evicted trace only exists as its
        # full-id file — the exact-name miss above must not make the
        # suggested `ray-tpu timeline --trace <prefix>` a dead end.
        try:
            names = [n for n in storage.listdir(trace_dir)
                     if n.endswith(".json") and n.startswith(tid)]
            if len(names) == 1:
                return json.loads(
                    storage.get_bytes(storage.join(trace_dir, names[0])))
        except Exception:
            pass
        return None

    async def _p_task_events(self, conn, a):
        self.task_events.extend(a["events"])

    # ------------------------------------------------------ event plane
    # README "Cluster events": the controller is the aggregation point for
    # lifecycle events — its own emissions (node/actor/lease/job
    # transitions), agent batches riding heartbeats/worker_died pushes, and
    # worker/driver batches riding metrics-flush frames.
    _EVENT_INDEX_PER_ENTITY = 128   # events kept per entity in the index
    _EVENT_INDEX_ENTITIES = 2048    # entities indexed (oldest-first evict)

    def _emit_event(self, kind: str, message: str = "", *,
                    severity: str | None = None, entity=(),
                    node_id: str | None = None,
                    trace_id: str | None = None,
                    attrs: dict | None = None) -> None:
        """Controller-side emission: mint + ingest directly (no ring hop)."""
        if int(CONFIG.events_buffer) <= 0:
            return
        from ray_tpu._private import events as _events

        self._ingest_events([_events.build_event(
            kind, message, severity=severity, entity=entity,
            node_id=node_id, trace_id=trace_id, attrs=attrs,
            src="controller")])

    def _ingest_events(self, evs: list, default_node: str | None = None) -> None:
        """Assign monotonic seqs in arrival order and index into the ring,
        the per-entity index, and the persistence buffer."""
        cap = int(CONFIG.events_buffer)
        if cap <= 0 or not evs:
            return
        persist = bool(CONFIG.events_persist)
        for ev in evs:
            if not isinstance(ev, dict) or not ev.get("kind"):
                continue
            ev["seq"] = self._event_seq
            self._event_seq += 1
            if ev.get("node") is None and default_node is not None:
                ev["node"] = default_node
            self.events.append(ev)
            while len(self.events) > cap:
                self.events.popleft()
            for eid in ev.get("entity") or ():
                # Pop + reinsert so dict order is last-TOUCHED: eviction
                # takes the coldest entity, not a hot long-lived one (the
                # head node's id gets events for the cluster's lifetime).
                dq = self._event_index.pop(eid, None)
                if dq is None:
                    while len(self._event_index) >= self._EVENT_INDEX_ENTITIES:
                        self._event_index.pop(
                            next(iter(self._event_index)), None)
                    dq = deque(maxlen=self._EVENT_INDEX_PER_ENTITY)
                self._event_index[eid] = dq
                dq.append(ev)
            if persist:
                self._evseg_buf.append(ev)
        if persist:
            # Bound the persistence backlog (backend severed/slow): shed
            # OLDEST — ring discipline, counted so the next successful
            # segment carries an events_dropped marker.
            lim = max(4 * int(CONFIG.events_segment_events), cap)
            over = len(self._evseg_buf) - lim
            if over > 0:
                del self._evseg_buf[:over]
                self._events_dropped += over
            if self._event_sweep_task is None and not self._stopping:
                try:
                    self._event_sweep_task = asyncio.ensure_future(
                        self._event_sweep())
                    self._tasks.append(self._event_sweep_task)
                except RuntimeError:
                    pass  # no running loop (unit tests drive persistence
                    #       synchronously via the sync helpers)

    def _event_hint(self, entity: str | None) -> str:
        """Error-message enrichment: the seq range of the events explaining
        an entity's fate, so an ActorDiedError/ObjectLostError names where
        to look ("" when the plane is off or the entity has no events)."""
        if not entity:
            return ""
        dq = self._event_index.get(entity)
        if not dq:
            return ""
        try:
            lo, hi = dq[0]["seq"], dq[-1]["seq"]
        except (IndexError, KeyError):
            return ""
        rng = str(lo) if lo == hi else f"{lo}-{hi}"
        return (f" [events {rng}: ray-tpu events --entity "
                f"{str(entity)[:12]}]")

    def _event_dir(self) -> str | None:
        if not CONFIG.events_persist or int(CONFIG.events_buffer) <= 0:
            return None
        d = CONFIG.events_dir
        if d:
            return d
        from ray_tpu._private import events as _events

        return _events.default_events_dir(self.session_id)

    _EVENT_SEG_RE = None  # compiled lazily (module re import stays top-free)

    @classmethod
    def _event_seg_seq(cls, name: str):
        """seg-<last_seq>.jsonl -> last_seq, else None."""
        import re

        if cls._EVENT_SEG_RE is None:
            cls._EVENT_SEG_RE = re.compile(r"^seg-(\d+)\.jsonl$")
        m = cls._EVENT_SEG_RE.match(name)
        return int(m.group(1)) if m else None

    def _restore_event_seq(self) -> None:
        """Boot-time restore of the event plane from persisted segments:
        (a) the seq fence — never mint a seq <= anything already persisted
        (segments outlive snapshots; the snapshot's watermark can lag the
        last sweep) — and (b) the queryable history: the newest
        ring-capacity worth of persisted events reload into the arrival
        ring + entity index, so `ray-tpu events` still answers "what
        happened" across a controller restart. current.jsonl's tail also
        refills the persistence buffer (those events live in NO full
        segment yet; the next tail rewrite must not drop them from
        durable storage)."""
        d = self._event_dir()
        if d is None:
            return
        try:
            import json as _json

            from ray_tpu import storage

            hi = self._event_seq - 1
            # listdir returns [] for a genuinely absent dir; an EXCEPTION
            # is a backend problem. Retry transient blips (the PR 8
            # _restore_state discipline): silently treating one as "no
            # history" would skip the seq fence and let this head re-mint
            # seqs that collide with (and later overwrite) persisted
            # segments.
            import time as _time

            names = None
            delay = 0.1
            for attempt in range(4):
                try:
                    names = storage.listdir(d)
                    break
                except storage.StorageTransientError:
                    if attempt == 3:
                        raise
                    _time.sleep(delay)
                    delay *= 2
            cap = max(1, int(CONFIG.events_buffer))
            segs = sorted((n for n in names
                           if self._event_seg_seq(n) is not None),
                          key=self._event_seg_seq)
            # Highest seq any FULL segment covers — strictly from segment
            # names, NOT the snapshot watermark: a watermark ahead of
            # persistence must not trick the tail refill below into
            # thinking current.jsonl's events are segment-covered (the
            # next tail rewrite would drop them from durable storage).
            seg_hi = -1
            for n in segs:
                seg_hi = max(seg_hi, self._event_seg_seq(n))
            hi = max(hi, seg_hi)
            by_seq: dict[int, dict] = {}
            # Newest segments first, until the ring capacity is covered.
            for n in reversed(segs):
                if len(by_seq) >= cap:
                    break
                try:
                    for ln in storage.get_bytes(
                            storage.join(d, n)).splitlines():
                        if ln.strip():
                            ev = _json.loads(ln)
                            if isinstance(ev.get("seq"), int):
                                by_seq[ev["seq"]] = ev
                except Exception:
                    pass
            tail: list = []
            if "current.jsonl" in names:
                try:
                    for ln in storage.get_bytes(
                            storage.join(d, "current.jsonl")).splitlines():
                        if ln.strip():
                            ev = _json.loads(ln)
                            if isinstance(ev.get("seq"), int):
                                tail.append(ev)
                except Exception:
                    pass
            # Dedup by seq: a crash between a seg-N write and the
            # current.jsonl rewrite leaves the tail in BOTH files — the
            # seq is the identity, so the duplicate collapses here (and
            # only tail events no segment covers refill the buffer below,
            # so it never becomes permanent in durable history).
            for ev in tail:
                hi = max(hi, ev["seq"])
                by_seq.setdefault(ev["seq"], ev)
            restored = [by_seq[s] for s in sorted(by_seq)][-cap:]
            for ev in restored:
                self.events.append(ev)
                for eid in ev.get("entity") or ():
                    dq = self._event_index.get(eid)
                    if dq is None:
                        dq = self._event_index[eid] = deque(
                            maxlen=self._EVENT_INDEX_PER_ENTITY)
                    dq.append(ev)
            # Tail events durable ONLY in current.jsonl (seq above every
            # full segment's) go back in the persistence buffer so they
            # roll into a real segment eventually.
            buf_tail = sorted((e for e in tail if e["seq"] > seg_hi),
                              key=lambda e: e["seq"])
            self._evseg_buf.extend(buf_tail)
            if buf_tail:
                self._evseg_tail_written = buf_tail[-1]["seq"]
            self._event_seq = max(self._event_seq, hi + 1)
        except Exception:
            logger.exception("event-plane restore failed; minting from "
                             "the snapshot watermark")

    async def _event_sweep(self):
        """Persist settled events as segmented JSONL through the storage
        plane, batched and OFF the event loop (the trace-sweep idiom). A
        failed tick (severed sim:// backend, storage blip) keeps the
        buffer and retries — persistence picks up when the backend heals
        (chaos-pinned)."""
        while not self._stopping:
            await asyncio.sleep(1.0)
            try:
                d = self._event_dir()
                if d is None:
                    self._evseg_buf.clear()
                    continue
                seg_n = max(16, int(CONFIG.events_segment_events))
                n_full = len(self._evseg_buf) // seg_n
                full = [list(self._evseg_buf[i * seg_n:(i + 1) * seg_n])
                        for i in range(n_full)]
                tail = list(self._evseg_buf[n_full * seg_n:])
                tail_hi = tail[-1]["seq"] if tail else -1
                if not full and tail_hi <= self._evseg_tail_written:
                    continue  # nothing new since the last write
                dropped, self._events_dropped = self._events_dropped, 0
                keep = max(1, int(CONFIG.events_keep_segments))
                loop = asyncio.get_running_loop()
                try:
                    await loop.run_in_executor(
                        None, self._persist_event_segments_sync, d, full,
                        tail, keep, dropped)
                except Exception:
                    self._events_dropped += dropped
                    raise
                # Success: full segments leave the buffer — BY SEQ, not by
                # count: the overflow shed in _ingest_events may have run
                # during the awaited write and already removed some of the
                # front, so a count-based del would take newer, never-
                # written events with it. The tail stays (it re-rolls into
                # the next full segment) but its write watermark advances
                # so quiet ticks skip the rewrite.
                if full:
                    written_hi = full[-1][-1]["seq"]
                    buf = self._evseg_buf
                    while buf and buf[0]["seq"] <= written_hi:
                        buf.pop(0)
                self._evseg_tail_written = tail_hi
                if dropped:
                    self._emit_event(
                        "events_dropped",
                        f"{dropped} event(s) shed while the events backend "
                        f"was unreachable", attrs={"count": dropped})
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("event persistence sweep tick failed; "
                                 "retrying")

    def _persist_event_segments_sync(self, events_dir: str, full: list,
                                     tail: list, keep: int,
                                     dropped: int) -> None:
        import json

        from ray_tpu import storage

        def _dump(evs):
            return ("\n".join(json.dumps(e, default=str)
                              for e in evs) + "\n").encode()

        with self._event_io_lock:
            for seg in full:
                storage.put(
                    storage.join(events_dir,
                                 f"seg-{seg[-1]['seq']:016d}.jsonl"),
                    _dump(seg))
            # The in-progress tail rewrites atomically each sweep so a
            # crash loses at most one tick of history. Watermark-gated: a
            # STALE writer (an executor sweep job that lost the race to
            # stop()'s final flush) must not overwrite a newer tail —
            # its coverage ends below what already landed.
            cover_hi = max(
                full[-1][-1]["seq"] if full else -1,
                tail[-1]["seq"] if tail else -1)
            if cover_hi >= self._evseg_current_hi:
                storage.put(storage.join(events_dir, "current.jsonl"),
                            _dump(tail) if tail else b"")
                self._evseg_current_hi = cover_hi
            if full:
                segs = sorted(
                    (n for n in storage.listdir(events_dir)
                     if self._event_seg_seq(n) is not None),
                    key=self._event_seg_seq)
                for victim in segs[:-keep] if len(segs) > keep else ():
                    try:
                        storage.delete(storage.join(events_dir, victim))
                    except Exception:
                        pass

    async def _h_list_events(self, conn, a):
        """Query the event ring: entity= (prefix-matches ANY of an event's
        entity ids, served from the secondary index), kind=, severity=,
        since= (seq, exclusive). Uniform truncation contract; `next_seq`
        feeds `ray-tpu events --follow` polling."""
        entity = a.get("entity") or None
        kind = a.get("kind") or None
        severity = a.get("severity") or None
        since = a.get("since")
        since = int(since) if since is not None else None
        limit = int(a.get("limit", 1000))
        if entity is not None:
            seen: dict[int, dict] = {}
            for eid, dq in self._event_index.items():
                if eid.startswith(entity):
                    for ev in dq:
                        seen[ev["seq"]] = ev
            rows = [seen[s] for s in sorted(seen)]
        else:
            rows = list(self.events)
        if kind is not None:
            rows = [e for e in rows if e.get("kind") == kind]
        if severity is not None:
            rows = [e for e in rows if e.get("sev") == severity]
        if since is not None:
            rows = [e for e in rows if e.get("seq", 0) > since]
        return {"events": rows[-limit:], "truncated": len(rows) > limit,
                "next_seq": self._event_seq,
                "dropped": self._events_dropped}

    # ------------------------------------------------------ stall detection
    async def _p_stall_report(self, conn, a):
        """One escalation-ladder stage observed somewhere in the cluster
        (worker watchdog via its node agent, agent backstop, or a train
        controller's group-stall policy). Aggregated into the stalls ring
        (util.state.list_stalls / `ray-tpu stalls`) and the
        rt_stalls_total{stage} counter."""
        if conn is not None and conn.meta.get("kind") == "node" \
                and self._fenced_node(conn, a) is None:
            return  # stale-incarnation zombie
        report = dict(a.get("report") or {})
        report.setdefault("node_id", a.get("node_id"))
        report["received"] = time.time()
        # Bound what the ring keeps per row: the full flight dump lives in
        # storage (report["flight_path"]); the ring is for triage listing.
        evs = report.get("events")
        if isinstance(evs, list) and len(evs) > 16:
            report["events"] = evs[-16:]
        stacks = report.get("stacks")
        if isinstance(stacks, str) and len(stacks) > 4000:
            report["stacks"] = stacks[-4000:]
        self.stalls.append(report)
        stage = str(report.get("stage") or "?")
        self._emit_event(
            "stall",
            f"stall {stage}: {report.get('name') or report.get('scope')} "
            f"silent {report.get('silence_s')}s — "
            f"{(report.get('reason') or '')[:120]}",
            severity=("error" if stage == "kill" else "warning"),
            entity=(report.get("task_id"), report.get("worker_id")),
            node_id=report.get("node_id"),
            trace_id=report.get("trace_id"),
            attrs={"stage": stage, "scope": report.get("scope"),
                   "silence_s": report.get("silence_s")})
        await self._p_metrics_report(None, {"records": [{
            "kind": "counter", "name": "rt_stalls_total",
            "desc": "stall escalations (warn/dump/kill stages observed)",
            "tags": {"stage": str(report.get("stage") or "?")},
            "value": 1.0}]})

    async def _h_list_stalls(self, conn, a):
        limit = int(a.get("limit", 1000))
        return {"stalls": list(self.stalls)[-limit:],
                "truncated": len(self.stalls) > limit}

    async def _h_task_status(self, conn, a):
        """Best-effort status of ONE task — the enrichment behind
        GetTimeoutError: queued/running, where, and seconds since its last
        progress beacon (when the stall watchdog is beaconing)."""
        tid = a["task_id"]
        out = {"found": False, "state": None, "name": None, "attempt": None,
               "node_id": None, "worker_id": None, "beacon_age_s": None}
        now = time.monotonic()
        for nid, (beacons, ts) in self._task_beacons.items():
            age = beacons.get(tid)
            if age is not None:
                out.update(found=True, state="running", node_id=nid,
                           beacon_age_s=round(age + (now - ts), 3))
                break
        info = self.dispatched.get(tid)
        if info is not None:
            out.update(found=True, state=out["state"] or "running",
                       node_id=info["node_id"], worker_id=info["worker_id"],
                       name=info["spec"].name, attempt=info["spec"].attempt)
            return out
        for spec in self.pending:
            if spec.task_id == tid:
                out.update(found=True, state="queued", name=spec.name,
                           attempt=spec.attempt)
                return out
        if not out["found"]:
            for ev in reversed(self.task_events):
                if ev["task_id"] == tid:
                    out.update(found=True,
                               state="finished" if ev["ok"] else "failed",
                               name=ev["name"], attempt=ev["attempt"],
                               node_id=ev["node_id"],
                               worker_id=ev["worker_id"])
                    break
        return out

    async def _h_get_task_events(self, conn, a):
        limit = int(a.get("limit", 100_000))
        evs = list(self.task_events)
        return {"events": evs[-limit:]}

    async def _h_list_tasks(self, conn, a):
        """Latest state per task (reference util/state/api.py list_tasks):
        executed tasks from the event ring + queued/dispatched live ones."""
        limit = int(a.get("limit", 1000))
        out: dict[str, dict] = {}
        for ev in self.task_events:
            out[ev["task_id"]] = {
                "task_id": ev["task_id"], "name": ev["name"],
                "kind": ev["kind"], "attempt": ev["attempt"],
                "state": "FINISHED" if ev["ok"] else "FAILED",
                "node_id": ev["node_id"], "worker_id": ev["worker_id"],
                "start": ev["start"], "end": ev["end"],
            }
        for spec in self.pending:
            out[spec.task_id] = {"task_id": spec.task_id, "name": spec.name,
                                 "kind": spec.kind, "attempt": spec.attempt,
                                 "state": "PENDING", "node_id": None,
                                 "worker_id": None, "start": None, "end": None}
        for tid, info in self.dispatched.items():
            out[tid] = {"task_id": tid, "name": info["spec"].name,
                        "kind": info["spec"].kind,
                        "attempt": info["spec"].attempt, "state": "RUNNING",
                        "node_id": info["node_id"],
                        "worker_id": info["worker_id"],
                        "start": None, "end": None}
        # Uniform truncation contract (shared by every list API): rows
        # beyond `limit` drop oldest-first and the reply says so instead
        # of silently shrinking.
        return {"tasks": list(out.values())[-limit:],
                "truncated": len(out) > limit}

    async def _h_list_objects(self, conn, a):
        import itertools

        limit = int(a.get("limit", 1000))
        total = len(self.objects)
        # Uniform truncation contract: oldest rows drop first (insertion
        # order), same as every other list API — but only the kept tail
        # is materialized (an O(table) dict build per call would stall
        # the event loop exactly when the table is large).
        out = [{"object_id": oid, "state": ent.state,
                "size": ent.size, "owner": ent.owner,
                "inline": ent.inline is not None,
                "plane": ent.plane or "host",
                "holders": [list(h) for h in ent.holders]}
               for oid, ent in itertools.islice(
                   self.objects.items(), max(0, total - limit), None)]
        return {"objects": out, "truncated": total > limit}

    async def _p_worker_logs(self, conn, a):
        """Fan worker stdout/stderr lines out to subscribed drivers
        (reference log_monitor.py -> GCS pubsub -> driver printer)."""
        for c in list(self.client_conns.values()):
            if c.meta.get("log_sub") and not c.closed and c is not conn:
                try:
                    await c.push("worker_log", **a)
                except Exception:
                    pass

    def _any_log_sub(self) -> bool:
        return any(c.meta.get("log_sub") and not c.closed
                   for c in self.client_conns.values())

    # ------------------------------------------------------------- pubsub
    # Reference src/ray/pubsub/publisher.h:300 (GCS pubsub channels for
    # actor state / node / job / error events) + user-defined channels.
    async def _h_subscribe(self, conn, a):
        subs = conn.meta.setdefault("subs", set())
        for ch in a.get("channels", ()):
            subs.add(ch)
        for ch in a.get("unsubscribe", ()):
            subs.discard(ch)
        return {"channels": sorted(subs)}

    async def _p_publish(self, conn, a):
        self._publish(a["channel"], a["payload"])

    def _publish_actor_state(self, ent) -> None:
        self._publish("actor", {
            "actor_id": ent.spec.actor_id, "state": ent.state,
            "name": ent.name, "node_id": ent.node_id,
            "restarts_used": ent.restarts_used})

    def _publish(self, channel: str, payload):
        for c in self.client_conns.values():
            if not c.closed and channel in (c.meta.get("subs") or ()):
                try:
                    c.push_threadsafe("pubsub", channel=channel, payload=payload)
                except Exception:
                    pass

    async def _h_subscribe_logs(self, conn, a):
        conn.meta["log_sub"] = bool(a.get("on", True))
        # Tell agents whether anyone is listening: unsubscribed clusters
        # must not pay per-line shipping costs.
        await self._push_log_sub_state(self._any_log_sub())
        return {}

    async def _push_log_sub_state(self, on: bool):
        for nconn in self.node_conns.values():
            if not nconn.closed:
                try:
                    await nconn.push("log_sub_state", on=on)
                except Exception:
                    pass

    async def _h_cluster_info(self, conn, a):
        """Bootstrap info for joining nodes/CLIs (reference: ray start
        --address fetches the session from the GCS)."""
        return {
            "session": self.session_id,
            "num_nodes": sum(1 for n in self.nodes.values() if n.alive),
        }

    async def _h_check_objects(self, conn, a):
        """Bulk readiness probe (backs `wait()`, cf. reference WaitManager
        raylet/wait_manager.h)."""
        out = []
        for oid in a["oids"]:
            ent = self.objects.get(oid)
            # "lost" counts as ready-to-return: wait() surfaces it so the
            # subsequent get() can raise / trigger lineage reconstruction.
            out.append(ent is not None and ent.state in ("ready", "lost"))
        return {"ready": out}

    async def _p_free_objects(self, conn, a):
        """Owner dropped its last reference. Only fan the purge out to node
        agents for objects that could actually have shm names there (a
        non-inline holder) — inline results (every small task/actor return)
        never touch /dev/shm, and purging them on every node made the agent
        glob shm per freed oid. Tombstones catch the advertise-vs-free race:
        a register that lands after the free must not resurrect the entry.

        Escaped oids (listed in a["escaped"], or marked on the entry) get
        borrower-protocol semantics instead: the entry is marked dying and
        survives until no borrowers remain and a grace TTL has passed
        (_sweep_dying) — the owner's local refcount hitting zero must not
        yank an object another process borrowed (reference
        reference_count.h borrower protocol)."""
        oids = a["oids"]
        escaped = set(a.get("escaped") or ())
        now = time.monotonic()
        if self.freed_tombstones and now > self._tombstone_prune_at:
            self._tombstone_prune_at = now + 10.0
            self.freed_tombstones = {
                o: t for o, t in self.freed_tombstones.items() if t > now}
        shm_oids = []
        device_frees: dict[str, list] = {}  # producer worker_id -> oids
        for oid in oids:
            ent = self.objects.get(oid)
            if oid in escaped or (ent is not None and ent.escaped):
                ent = self.objects.setdefault(oid, _ObjectEntry())
                ent.escaped = True
                if ent.dying_at is None:
                    ent.dying_at = now + CONFIG.borrowed_free_grace_s
                continue
            self.objects.pop(oid, None)
            # TTL must exceed any plausible task runtime: a fire-and-forget
            # task finishing after the tombstone expires would resurrect the
            # entry (and pin its shm segment forever).
            self.freed_tombstones[oid] = now + 600.0
            if ent is not None and ent.plane == "device":
                # Device-plane entry: the payload is pinned in the producing
                # process — unpin it with a TARGETED device_free on that
                # producer's own client connection (works for driver
                # producers too, which no agent can reach), and purge the
                # shm export names everywhere like any other segment.
                if ent.device_worker:
                    device_frees.setdefault(ent.device_worker, []).append(oid)
                self._device_index_drop(ent, oid)
                shm_oids.append(oid)
            elif ent is not None and ent.inline is None and ent.holders:
                shm_oids.append(oid)
        if len(self.freed_tombstones) > 200_000:  # hard cap, oldest first
            for o in list(self.freed_tombstones)[:100_000]:
                self.freed_tombstones.pop(o, None)
        if shm_oids:
            await self._purge_on_agents(shm_oids)
        await self._push_device_frees(device_frees)

    async def _purge_on_agents(self, shm_oids: list[str]):
        for nconn in self.node_conns.values():
            if not nconn.closed:
                try:
                    await nconn.push("free", oids=shm_oids)
                except Exception:
                    pass

    async def _push_device_frees(self, by_worker: dict):
        """Unpin freed device objects at their producers: ONE device_free
        push per producing process over its registered client connection
        (executing workers and drivers both register as clients) — not a
        cluster-wide broadcast."""
        for worker_id, oids in by_worker.items():
            conn = self.client_conns.get(worker_id)
            if conn is not None and not conn.closed:
                try:
                    await conn.push("device_free", oids=oids)
                except Exception:
                    pass

    async def _p_borrow_add(self, conn, a):
        """A process materialized a borrowed ref: pin the entry while the
        borrower lives (keeps a dying escaped entry alive past its TTL)."""
        if self._freed(a["oid"]):
            # The object is already gone (grace expired / non-escaped free):
            # don't resurrect a permanently-pending entry — the borrower's
            # get() will surface 'lost' via the tombstone.
            return
        ent = self.objects.setdefault(a["oid"], _ObjectEntry())
        ent.escaped = True
        ent.borrowers.add(a["worker_id"])

    async def _p_borrow_drop(self, conn, a):
        ent = self.objects.get(a["oid"])
        if ent is None:
            return
        ent.borrowers.discard(a["worker_id"])
        # Even with no borrowers left, the entry must survive until its
        # grace TTL: another borrow registration may still be in flight
        # (that window is the whole reason dying_at exists). The health
        # loop's _sweep_dying reaps it at the TTL.

    async def _free_escaped(self, oids: list[str]):
        now = time.monotonic()
        shm_oids = []
        device_frees: dict[str, list] = {}
        for oid in oids:
            ent = self.objects.pop(oid, None)
            self.freed_tombstones[oid] = now + 600.0
            if ent is not None and ent.plane == "device":
                if ent.device_worker:
                    device_frees.setdefault(ent.device_worker, []).append(oid)
                self._device_index_drop(ent, oid)
                shm_oids.append(oid)
            elif ent is not None and ent.inline is None and ent.holders:
                shm_oids.append(oid)
        if shm_oids:
            await self._purge_on_agents(shm_oids)
        await self._push_device_frees(device_frees)

    async def _sweep_dying(self):
        """Reap owner-freed escaped entries whose grace TTL expired with no
        registered borrowers (runs from the health loop)."""
        now = time.monotonic()
        expired = [oid for oid, ent in self.objects.items()
                   if ent.dying_at is not None and now >= ent.dying_at
                   and not ent.borrowers]
        if expired:
            await self._free_escaped(expired)

    def _freed(self, oid: str) -> bool:
        t = self.freed_tombstones.get(oid)
        if t is None:
            return False
        if t <= time.monotonic():
            self.freed_tombstones.pop(oid, None)
            return False
        return True

    async def _purge_late(self, oid: str, holder,
                          device_worker: str | None = None):
        """A result advertised after its ref was freed: purge the shm names
        it just created (fire-and-forget tasks with large returns). A late
        DEVICE advertise also unpins at the producer — otherwise the pin
        (and the device memory under it) would outlive the freed ref."""
        if device_worker:
            await self._push_device_frees({device_worker: [oid]})
        if holder is None and not device_worker:
            return
        for nconn in self.node_conns.values():
            if not nconn.closed:
                try:
                    await nconn.push("free", oids=[oid])
                except Exception:
                    pass

    # ------------------------------------------------------------- actors
    async def _h_create_actor(self, conn, a):
        spec = self._ingest_spec(conn, a["spec"])
        if spec.actor_name:
            key = (spec.namespace, spec.actor_name)
            existing = self.named_actors.get(key)
            if existing is not None and self.actors[existing].state != "DEAD":
                if spec.get_if_exists:
                    return {"actor_id": existing, "existing": True}
                raise rpc.RpcError(f"Actor name {spec.actor_name!r} already taken")
            self.named_actors[key] = spec.actor_id
        self.actors[spec.actor_id] = _ActorEntry(spec)
        self._mark_dirty()
        self.pending.append(spec)
        self._emit_event("actor_create",
                         f"actor {spec.name} ({spec.actor_id[:12]}) queued",
                         entity=(spec.actor_id,),
                         attrs={"name": spec.name})
        self._kick()
        return {"actor_id": spec.actor_id, "existing": False}

    async def _actor_started(self, spec: TaskSpec, a: dict, info):
        ent = self.actors.get(spec.actor_id)
        if ent is None:
            return
        if ent.state == "DEAD":
            # Killed while __init__ was running: do not resurrect; reap the
            # worker and release whatever _dispatch accounted to it.
            if ent.worker_id is not None and ent.node_id in self.node_conns:
                try:
                    await self.node_conns[ent.node_id].push(
                        "kill_worker", worker_id=ent.worker_id)
                except Exception:
                    pass
            self._release_actor_resources(ent)
            return
        if a.get("error") is not None:
            # Actor __init__ raised: actor is DEAD with that cause.
            ent.state = "DEAD"
            self._publish_actor_state(ent)
            ent.death_cause = a["error"]
            self._release_actor_resources(ent)
            self._mark_dirty()
            self._emit_event(
                "actor_death",
                f"actor {spec.name} ({spec.actor_id[:12]}) died: __init__ "
                f"raised", entity=(spec.actor_id, ent.worker_id),
                node_id=ent.node_id)
            ent.wake()
            return
        ent.state = "ALIVE"
        self._publish_actor_state(ent)
        ent.address = tuple(a["actor_address"])
        if ent.worker_id:
            self._actor_host_workers.add(ent.worker_id)
        ent.instance += 1
        self._emit_event(
            "actor_ready",
            f"actor {spec.name} ({spec.actor_id[:12]}) alive "
            f"(instance {ent.instance})",
            entity=(spec.actor_id, ent.worker_id), node_id=ent.node_id,
            attrs={"instance": ent.instance})
        ent.wake()
        logger.info("actor %s alive at %s", spec.name, ent.address)

    def _release_actor_resources(self, ent: _ActorEntry):
        if not ent.resources_held:
            return  # already released for this instance (idempotent)
        ent.resources_held = False
        if ent.node_id is not None:
            node = self.nodes.get(ent.node_id)
            if node is not None and node.liveness != "DEAD":
                self._release(ent.node_id, ent.spec, ResourceSet(_raw=ent.spec.resources))
            self._kick()

    async def _h_get_actor_info(self, conn, a):
        actor_id = a.get("actor_id")
        if actor_id is None:
            key = (a.get("namespace", "default"), a["name"])
            actor_id = self.named_actors.get(key)
            if actor_id is None:
                return {"status": "not_found"}
        ent = self.actors.get(actor_id)
        if ent is None:
            return {"status": "not_found"}
        deadline = time.monotonic() + a.get("timeout", 60.0)
        while ent.state in ("PENDING", "RESTARTING", "RECOVERING") and a.get("wait", True):
            fut = asyncio.get_running_loop().create_future()
            ent.waiters.append(fut)
            try:
                await asyncio.wait_for(fut, max(0.0, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                break
        return {
            "status": "ok",
            "actor_id": actor_id,
            "state": ent.state,
            "address": ent.address,
            "instance": ent.instance,
            "worker_id": ent.worker_id,
            "death_cause": ent.death_cause,
            "max_task_retries": ent.spec.max_task_retries,
        }

    async def _reap_owned_actors(self, owner: str, owner_mode):
        """Ownership fate-sharing (reference gcs_actor_manager
        OnWorkerDead/OnJobFinished): when a DRIVER or an actor-hosting
        worker disconnects, its non-detached actors die with it. Pooled
        task workers are exempt — they exit routinely (idle reaping) and a
        task-created actor must outlive the transient worker that ran the
        creating task."""
        if owner_mode != "driver" and owner not in self._actor_host_workers:
            return
        for aid, ent in list(self.actors.items()):
            if (ent.spec.owner_id == owner and ent.state != "DEAD"
                    and ent.spec.lifetime != "detached"):
                logger.info("actor %s dies with its owner %s (fate-sharing)",
                            aid[:8], owner[:8])
                ent.spec.max_restarts = 0
                if ent.state in ("RESTARTING", "PENDING"):
                    # No live instance to kill and _actor_worker_died would
                    # no-op: cancel the queued respawn and bury it directly.
                    for spec in list(self.pending):
                        if spec.actor_id == aid:
                            self.pending.remove(spec)
                    self._bury_actor(ent, "owner disconnected (fate-sharing)")
                    continue
                wid = ent.worker_id
                if wid is not None and ent.node_id in self.node_conns:
                    try:
                        await self.node_conns[ent.node_id].push(
                            "kill_worker", worker_id=wid)
                    except Exception:
                        pass
                await self._actor_worker_died(
                    aid, "owner disconnected (fate-sharing)", worker_id=wid)

    def _bury_actor(self, ent, reason: str):
        from ray_tpu._private.serialization import dumps_oob

        ent.state = "DEAD"
        self._publish_actor_state(ent)
        aid = ent.spec.actor_id
        self._emit_event("actor_death",
                         f"actor {ent.spec.name} ({aid[:12]}) died: {reason}",
                         entity=(aid,), attrs={"reason": reason})
        h, b = dumps_oob({"type": "ActorDiedError",
                          "message": reason + self._event_hint(aid)})
        ent.death_cause = [h, *b]
        self._release_actor_resources(ent)
        self._mark_dirty()
        ent.wake()
        if ent.name:
            self.named_actors.pop((ent.namespace, ent.name), None)

    async def _h_kill_actor(self, conn, a):
        ent = self.actors.get(a["actor_id"])
        if ent is None:
            return {}
        if a.get("no_restart", True):
            ent.spec.max_restarts = 0
        wid = ent.worker_id
        if wid is not None and ent.node_id in self.node_conns:
            try:
                await self.node_conns[ent.node_id].push("kill_worker", worker_id=wid)
            except Exception:
                pass
        await self._actor_worker_died(a["actor_id"], "killed via kill()", worker_id=wid)
        return {}

    async def _maybe_restart_actor(self, actor_id: str, reason: str):
        ent = self.actors.get(actor_id)
        if ent is None:
            return
        max_restarts = ent.spec.max_restarts
        if max_restarts == -1 or ent.restarts_used < max_restarts:
            ent.restarts_used += 1
            ent.state = "RESTARTING"
            self._publish_actor_state(ent)
            ent.address = None
            logger.info("restarting actor %s (%d used): %s", ent.spec.name, ent.restarts_used, reason)
            self._emit_event(
                "actor_restart",
                f"actor {ent.spec.name} ({actor_id[:12]}) restarting "
                f"({ent.restarts_used} used): {reason}",
                entity=(actor_id,),
                attrs={"restarts_used": ent.restarts_used,
                       "reason": reason})
            respawn = ent.spec
            respawn.attempt += 1
            self.pending.append(respawn)
            self._kick()
        else:
            ent.state = "DEAD"
            self._publish_actor_state(ent)
            from ray_tpu._private.serialization import dumps_oob

            self._emit_event(
                "actor_death",
                f"actor {ent.spec.name} ({actor_id[:12]}) died: {reason}",
                entity=(actor_id,), attrs={"reason": reason})
            # Error enrichment (README "Cluster events"): the error a
            # caller sees names the event seqs that explain the death.
            h, b = dumps_oob({"type": "ActorDiedError",
                              "message": reason + self._event_hint(actor_id)})
            ent.death_cause = [h, *b]
            self._release_actor_resources(ent)
            self._mark_dirty()
            ent.wake()
            if ent.name:
                self.named_actors.pop((ent.namespace, ent.name), None)

    def _device_index_drop(self, ent, oid: str) -> None:
        if ent.device_worker:
            s = self._device_index.get(ent.device_worker)
            if s is not None:
                s.discard(oid)
                if not s:
                    self._device_index.pop(ent.device_worker, None)

    async def _mark_device_lost(self, oid: str, ent, message: str):
        """One device entry's payload died with its producer: flip the
        entry to lost and tell the owner, so a consumer's get() surfaces a
        clean ObjectLostError NAMING the lost producer instead of hanging
        on a dead address."""
        ent.state = "lost"
        ent.inline = None
        ent.wake()
        self._device_index_drop(ent, oid)
        oconn = self.client_conns.get(ent.owner)
        if oconn is not None and not oconn.closed:
            try:
                await oconn.push("object_lost", oid=oid, message=message)
            except Exception:
                pass

    async def _device_objects_lost(self, worker_id: str, why: str):
        """A worker process died taking its DeviceObjectTable with it.
        Idempotent: already-lost entries are skipped. O(that worker's
        entries) via the device index — routine worker exits on clusters
        that never touch the plane cost nothing."""
        oids = self._device_index.pop(worker_id, None)
        if not oids:
            return
        self._emit_event(
            "device_objects_lost",
            f"{len(oids)} device object(s) lost: producing worker "
            f"{worker_id[:12]} {why}",
            entity=(worker_id,), attrs={"count": len(oids)})
        hint = self._event_hint(worker_id)
        for oid in oids:
            ent = self.objects.get(oid)
            if ent is None or ent.plane != "device" or ent.state != "ready":
                continue
            await self._mark_device_lost(
                oid, ent,
                f"device object {oid[:16]} lost: producing worker "
                f"{worker_id[:12]} {why}" + hint)

    async def _actor_worker_died(self, actor_id: str, reason: str,
                                 worker_id: str | None = None,
                                 device_swept: bool = False):
        """Process the death of one actor *instance*. Idempotent: each
        instance's death is consumed exactly once (keyed by the instance's
        worker_id), so a kill() followed by the agent's worker_died report
        cannot double-release resources or double-restart (round-1 advisor
        finding; reference keys restarts by actor instance in
        gcs_actor_manager.cc)."""
        ent = self.actors.get(actor_id)
        if ent is None or ent.state == "DEAD":
            return
        if worker_id is not None:
            if ent.worker_id != worker_id:
                return  # stale report for an already-handled instance
        elif ent.state == "RESTARTING":
            return  # death already being handled; a restart is in flight
        # Device objects pinned in this instance die with it (kill() skips
        # the agent's worker_died report, so this is the kill path's sweep;
        # _p_worker_died already swept when it is the caller).
        wid = worker_id or ent.worker_id
        if wid and not device_swept:
            await self._device_objects_lost(wid, f"died ({reason})")
            self._telem_purge_worker(wid)
        # Drop any in-flight creation bookkeeping.
        self.dispatched.pop(ent.spec.task_id, None)
        self._release_actor_resources(ent)
        ent.worker_id = None  # instance death consumed
        ent.address = None
        await self._maybe_restart_actor(actor_id, reason)

    async def _p_worker_died(self, conn, a):
        """Node agent reports a worker process exit. `cause="oom"` marks a
        memory-monitor kill so owners surface OutOfMemoryError."""
        if conn is not None and conn.meta.get("kind") == "node" \
                and self._fenced_node(conn, a) is None:
            return  # stale-incarnation zombie: must not kill current state
        # The agent's pending events (incl. this death's worker_exit) ride
        # the report itself, so their seqs land BEFORE the restart/failover
        # events this handler mints — causal chains stay ordered.
        evs = a.get("events")
        if evs:
            self._ingest_events(evs, default_node=a.get("node_id"))
        cause = a.get("cause")
        if a.get("worker_id"):
            await self._device_objects_lost(a["worker_id"], "process died")
            await self._lease_worker_died(a["worker_id"], cause=cause)
            self._telem_purge_worker(a["worker_id"])
        actor_id = a.get("actor_id")
        task_id = a.get("task_id")
        if actor_id:
            await self._actor_worker_died(
                actor_id, f"worker process died: {a.get('reason', '')}",
                worker_id=a.get("worker_id"),
                device_swept=bool(a.get("worker_id")))
        if task_id:
            info = self.dispatched.pop(task_id, None)
            if info is not None:
                spec = info["spec"]
                if spec.kind != ACTOR_CREATE:
                    self._release(info["node_id"], spec, ResourceSet(_raw=spec.resources))
                await self._retry_or_fail(
                    spec, a.get("reason") or "worker process died",
                    error_type="OutOfMemoryError" if cause == "oom" else None)
                self._kick()

    # ------------------------------------------------------- node failure
    async def _node_suspect(self, nid: str, conn=None):
        """The node's control connection closed. Instead of declaring it
        dead (and restarting ALIVE actors whose workers are still serving
        their direct pipes — split-brain duplicate actors on a TCP blip),
        move it to SUSPECT for a grace window: leases and actors are
        FROZEN — kept, charged, not restarted — and the node is
        unschedulable. An agent re-registration within the window
        reconciles in place (_h_register); only expiry promotes to DEAD."""
        node = self.nodes.get(nid)
        if node is None or node.liveness != "ALIVE":
            return
        if conn is not None and conn.meta.get("incarnation") != node.incarnation:
            # The agent re-registered between the close callback's fence
            # check and this task running: the close belongs to a previous
            # life, and suspecting the NEW life would kill a healthy node
            # at grace expiry (nothing would ever clear the suspicion).
            return
        grace = CONFIG.node_suspect_grace_s
        if grace <= 0:  # configured off: the old kill-on-close behavior
            await self._node_died(nid)
            return
        node.liveness = "SUSPECT"
        node.suspect_since = time.monotonic()
        incarnation = node.incarnation
        if conn is None or self.node_conns.get(nid) is conn:
            self.node_conns.pop(nid, None)
        logger.warning("node %s connection lost; SUSPECT for %.1fs grace "
                       "(incarnation %d)", nid[:8], grace, incarnation)
        self._emit_event(
            "node_suspect",
            f"node {nid[:8]} connection lost; SUSPECT for {grace:.1f}s",
            entity=(nid,), node_id=nid,
            attrs={"incarnation": incarnation, "grace_s": grace})
        self._publish("node", {"node_id": nid, "alive": False,
                               "liveness": "SUSPECT"})
        await asyncio.sleep(grace)
        current = self.nodes.get(nid)
        if (current is node and node.liveness == "SUSPECT"
                and node.incarnation == incarnation):
            logger.warning("node %s suspicion grace expired; declaring dead",
                           nid[:8])
            await self._node_died(nid)

    async def _reconcile_returned_node(self, nid: str, node: NodeState,
                                       reported: list):
        """A SUSPECT (or racing-ALIVE) node's agent re-registered within the
        grace window. The NodeState — and with it all resource accounting —
        survived the blip, so only the DIFF needs work: anything the agent
        no longer reports died during the outage and takes the normal death
        paths now; everything else stays bound exactly as it was (running
        calls on direct worker pipes never noticed)."""
        by_wid = {w["worker_id"]: w for w in reported}
        # ALIVE actors hosted here: re-bind to their surviving workers (and
        # cancel any queued re-creation a racing path produced); restart the
        # ones whose workers died during the blip.
        for aid, ent in list(self.actors.items()):
            if ent.node_id != nid or ent.state != "ALIVE":
                continue
            w = by_wid.get(ent.worker_id)
            if w is not None and (w.get("actor_id") in (None, aid)):
                for spec in list(self.pending):
                    if spec.actor_id == aid:
                        self.pending.remove(spec)  # cancel queued re-creation
                if w.get("address"):
                    ent.address = tuple(w["address"])
            else:
                await self._actor_worker_died(
                    aid, f"worker died during node {nid[:8]} suspicion blip",
                    worker_id=ent.worker_id)
        # Tasks this controller dispatched to the node: retry the ones whose
        # workers are gone (their task_done can never come). A worker can be
        # missing from inventory while still SPAWNING (no address yet), so
        # reap it explicitly — its work is being retried elsewhere, and a
        # dedicated worker finishing startup later would otherwise be
        # orphaned on the node forever with its accounting already released.
        nconn = self.node_conns.get(nid)
        for task_id, info in list(self.dispatched.items()):
            if info["node_id"] != nid or info["worker_id"] in by_wid:
                continue
            self.dispatched.pop(task_id, None)
            if nconn is not None and not nconn.closed:
                try:
                    await nconn.push("kill_worker",
                                     worker_id=info["worker_id"])
                except Exception:
                    pass
            spec = info["spec"]
            if spec.kind == ACTOR_CREATE:
                # The idempotent instance-death path: releases the held
                # resources before deciding restart-vs-bury.
                await self._actor_worker_died(
                    spec.actor_id,
                    f"worker died during node {nid[:8]} suspicion blip",
                    worker_id=info["worker_id"])
                continue
            self._release(nid, spec, ResourceSet(_raw=spec.resources))
            await self._retry_or_fail(
                spec, f"worker died during node {nid[:8]} suspicion blip")
        # Leases whose workers died during the blip: invalidate so owners
        # requeue their in-flight specs (surviving leases stay untouched —
        # their direct pipes were never involved in the outage).
        for lease_id, ent in list(self.leases.items()):
            if ent["node_id"] == nid and ent["worker_id"] not in by_wid:
                await self._lease_worker_died(ent["worker_id"])
        # Inventory sweep for bindings that dissolved DURING the blip, when
        # no kill/unlease push could reach the agent: an actor that was
        # kill()ed or restarted away leaves a zombie instance still serving
        # its pipes (exactly one instance may live — reap it); a lease that
        # was returned/reaped leaves the slot stuck 'leased' forever.
        # Warm-pool entries are forgotten first so their slots fall to the
        # sweep's unlease too (pool regrants must not outlive a blip).
        self._drop_node_pool(nid)
        lease_wids = {l["worker_id"] for l in self.leases.values()}
        nconn = self.node_conns.get(nid)
        for w in reported:
            wid = w["worker_id"]
            aid = w.get("actor_id")
            if aid:
                ent = self.actors.get(aid)
                # PENDING/RECOVERING stay: an in-flight creation's worker is
                # judged by the dispatched-tasks loop above, not reaped.
                if ent is None or ent.state not in ("DEAD", "RESTARTING",
                                                    "ALIVE"):
                    continue
                if ent.state == "ALIVE" and ent.worker_id == wid:
                    continue  # correctly re-bound above
                await self._reap_stale_worker(
                    nid, wid, aid, f"entry is {ent.state} after the blip")
            elif w.get("state") == "leased" and wid not in lease_wids:
                if nconn is not None and not nconn.closed:
                    try:
                        await nconn.push("unlease_worker", worker_id=wid)
                    except Exception:
                        pass
        self._kick()

    async def _node_died(self, nid: str):
        node = self.nodes.get(nid)
        if node is None or node.liveness == "DEAD":
            return
        node.liveness = "DEAD"
        self.node_conns.pop(nid, None)
        self._drop_node_pool(nid)
        self._task_beacons.pop(nid, None)
        self._reconciled_busy = {
            t: (n, r) for t, (n, r) in self._reconciled_busy.items()
            if n != nid}
        logger.warning("node %s died", nid[:8])
        self._emit_event("node_dead", f"node {nid[:8]} declared dead",
                         entity=(nid,), node_id=nid)
        self._publish("node", {"node_id": nid, "alive": False})
        # Invalidate leases whose worker lived there — same event + cause
        # vocabulary as the single-worker death path (_lease_worker_died),
        # so node-death failovers are queryable too.
        from ray_tpu._private import events as _events

        for lease_id, ent in list(self.leases.items()):
            if ent["node_id"] == nid:
                self._drop_lease(lease_id)  # node dead: release is a no-op
                self._emit_event(
                    "lease_failover",
                    f"lease {lease_id[:8]} invalidated: node {nid[:8]} "
                    f"died with worker {ent['worker_id'][:12]}; in-flight "
                    f"specs fail over",
                    entity=(lease_id, ent["worker_id"], ent["owner"], nid),
                    node_id=nid, attrs={"cause": _events.CAUSE_CRASH})
                oconn = self.client_conns.get(ent["owner"])
                if oconn is not None and not oconn.closed:
                    try:
                        await oconn.push("lease_invalid", lease_id=lease_id,
                                         cause=_events.CAUSE_CRASH)
                    except Exception:
                        pass
        # Retry tasks that were running there.
        for task_id, info in list(self.dispatched.items()):
            if info["node_id"] == nid:
                self.dispatched.pop(task_id, None)
                await self._retry_or_fail(info["spec"], f"node {nid[:8]} died")
        # Jobs whose driver ran there can't finish.
        for job in self.jobs.values():
            if job["node_id"] == nid and job["status"] in ("PENDING", "RUNNING"):
                job["status"] = "FAILED"
                job["message"] = f"node {nid[:8]} hosting the job driver died"
                job["end_time"] = time.time()
                self._emit_event(
                    "job_stop",
                    f"job {job['submission_id']} -> FAILED (node {nid[:8]} "
                    f"hosting the job driver died)", severity="warning",
                    entity=(job["submission_id"], nid), node_id=nid,
                    attrs={"status": "FAILED"})
        # Restart/kill its actors.
        for actor_id, ent in list(self.actors.items()):
            if ent.node_id == nid and ent.state in ("ALIVE", "PENDING", "RESTARTING"):
                ent.resources_held = False  # node gone; nothing to give back
                ent.worker_id = None
                ent.address = None
                await self._maybe_restart_actor(actor_id, f"node {nid[:8]} died")
        # Mark objects whose only copies were there as lost -> owners may
        # reconstruct from lineage (reference object_recovery_manager.cc:26).
        dead_addr = node.address
        for oid, ent in list(self.objects.items()):  # handlers may insert during awaits
            if ent.plane == "device":
                # Device entries hold only a placeholder inline; the payload
                # lived in a worker on the node. Every producer there died
                # with it.
                if ent.device_node == nid and ent.state == "ready":
                    await self._mark_device_lost(
                        oid, ent,
                        f"device object {oid[:16]} lost: producing worker "
                        f"{(ent.device_worker or '?')[:12]} died with node "
                        f"{nid[:8]}" + self._event_hint(nid))
                continue
            if ent.state != "ready" or ent.inline is not None:
                continue
            ent.holders = {h for h in ent.holders if tuple(h) != tuple(dead_addr)}
            if not ent.holders and ent.error is None:
                ent.state = "lost"
                ent.wake()
                owner_conn = self.client_conns.get(ent.owner)
                if owner_conn is not None and not owner_conn.closed:
                    try:
                        await owner_conn.push("object_lost", oid=oid)
                    except Exception:
                        pass
        # PG bundles on the node are lost.
        for (pgid, idx), b in list(self.pg_bundles.items()):
            if b["node"] == nid:
                self.pgs[pgid]["state"] = "RESCHEDULING"
        self._kick()

    async def _health_loop(self):
        interval = CONFIG.heartbeat_interval_s
        timeout = interval * CONFIG.num_heartbeats_timeout
        while True:
            await asyncio.sleep(interval)
            now = time.monotonic()
            for nid, node in list(self.nodes.items()):
                if node.alive and node.last_beat and now - node.last_beat > timeout:
                    await self._node_died(nid)
                elif (node.liveness == "SUSPECT" and now - node.suspect_since
                        > CONFIG.node_suspect_grace_s + interval):
                    # Belt and braces: the per-suspicion expiry task owns
                    # promotion to DEAD; this catches it getting lost.
                    await self._node_died(nid)
            try:
                await self._sweep_dying()
            except Exception:
                logger.exception("dying-object sweep failed")
            try:
                if self.lease_pool:
                    await self._sweep_lease_pool()
            except Exception:
                logger.exception("lease-pool sweep failed")

    # ----------------------------------------------------- placement groups
    async def _h_create_pg(self, conn, a):
        pg_id = a["pg_id"]
        bundles = [ResourceSet(_raw=raw) for raw in a["bundles"]]
        strategy = a.get("strategy", "PACK")
        placed = self._place_bundles(bundles, strategy)
        if placed is None:
            self.pgs[pg_id] = {"state": "PENDING", "bundles_raw": a["bundles"], "strategy": strategy, "name": a.get("name")}
            return {"state": "PENDING"}
        for idx, (nid, rs) in enumerate(placed):
            self.nodes[nid].available.subtract(rs)
            self.pg_bundles[(pg_id, idx)] = {"node": nid, "available": rs.copy(), "reserved": rs}
        self.pgs[pg_id] = {"state": "CREATED", "bundles_raw": a["bundles"], "strategy": strategy, "name": a.get("name")}
        self._mark_dirty()
        self._kick()
        return {"state": "CREATED"}

    def _place_bundles(self, bundles: list[ResourceSet], strategy: str):
        """2-phase prepare/commit is unnecessary with a central scheduler —
        placement is atomic here (cf. reference GcsPlacementGroupScheduler)."""
        avail = {nid: n.available.copy() for nid, n in self.nodes.items()
                 if n.alive and not n.draining}
        placed: list[tuple[str, ResourceSet]] = []
        used_nodes: set[str] = set()
        for rs in bundles:
            candidates = [nid for nid, av in avail.items() if av.fits(rs)]
            if strategy in ("STRICT_SPREAD", "SPREAD"):
                fresh = [nid for nid in candidates if nid not in used_nodes]
                if strategy == "STRICT_SPREAD":
                    candidates = fresh
                elif fresh:
                    candidates = fresh
            elif strategy == "STRICT_PACK":
                if used_nodes:
                    candidates = [nid for nid in candidates if nid in used_nodes]
            else:  # PACK: prefer already-used nodes
                pref = [nid for nid in candidates if nid in used_nodes]
                if pref:
                    candidates = pref
            if not candidates:
                return None
            nid = sorted(candidates)[0]
            avail[nid].subtract(rs)
            placed.append((nid, rs))
            used_nodes.add(nid)
        return placed

    def _try_place_pg(self, pg_id: str, pg: dict) -> bool:
        """Place + commit a PG's bundles; True on success (state CREATED,
        dirty marked). The ONE implementation all creation/retry paths use."""
        bundles = [ResourceSet(_raw=raw) for raw in pg["bundles_raw"]]
        placed = self._place_bundles(bundles, pg["strategy"])
        if placed is None:
            return False
        for idx, (nid, rs) in enumerate(placed):
            self.nodes[nid].available.subtract(rs)
            self.pg_bundles[(pg_id, idx)] = {
                "node": nid, "available": rs.copy(), "reserved": rs}
        pg["state"] = "CREATED"
        self._mark_dirty()
        return True

    def _retry_pending_pgs(self):
        """Place PENDING placement groups (restored from a snapshot or
        waiting for capacity) — runs when nodes join."""
        for pg_id, pg in self.pgs.items():
            if pg["state"] == "PENDING":
                self._try_place_pg(pg_id, pg)

    async def _h_pg_wait_ready(self, conn, a):
        deadline = time.monotonic() + a.get("timeout", 30.0)
        pg_id = a["pg_id"]
        while time.monotonic() < deadline:
            pg = self.pgs.get(pg_id)
            if pg is None:
                return {"ready": False, "reason": "removed"}
            if pg["state"] == "CREATED":
                return {"ready": True}
            # Retry placement (nodes may have joined/freed).
            if self._try_place_pg(pg_id, pg):
                self._kick()
                return {"ready": True}
            await asyncio.sleep(0.05)
        return {"ready": False, "reason": "timeout"}

    async def _h_remove_pg(self, conn, a):
        pg_id = a["pg_id"]
        self.pgs.pop(pg_id, None)
        self._mark_dirty()
        for (pgid, idx) in list(self.pg_bundles):
            if pgid == pg_id:
                b = self.pg_bundles.pop((pgid, idx))
                node = self.nodes.get(b["node"])
                # SUSPECT accounting is frozen, not discarded: skipping the
                # release would leave the node permanently undercounted
                # after it reconciles back to ALIVE.
                if node is not None and node.liveness != "DEAD":
                    node.available.add(b["reserved"])
        self._kick()
        return {}

    # ------------------------------------------------------------------ KV
    async def _h_kv_put(self, conn, a):
        key = (a.get("ns", ""), a["key"])
        if a.get("overwrite", True) or key not in self.kv:
            self.kv[key] = a["value"]
            self._mark_dirty()
            return {"added": True}
        return {"added": False}

    async def _h_kv_get(self, conn, a):
        return {"value": self.kv.get((a.get("ns", ""), a["key"]))}

    async def _h_kv_del(self, conn, a):
        deleted = self.kv.pop((a.get("ns", ""), a["key"]), None) is not None
        if deleted:
            self._mark_dirty()
        return {"deleted": deleted}

    async def _h_kv_exists(self, conn, a):
        return {"exists": (a.get("ns", ""), a["key"]) in self.kv}

    async def _h_kv_keys(self, conn, a):
        ns = a.get("ns", "")
        prefix = a.get("prefix", "")
        return {"keys": [k for (n, k) in self.kv if n == ns and k.startswith(prefix)]}

    # ------------------------------------------------------------ state API
    async def _h_kill_node(self, conn, a):
        """Explicit node removal (cluster_utils.remove_node, scale-down
        termination): skips the suspicion grace window — an operator kill
        is a fact, not a connection blip — and runs the death path now."""
        nid = a["node_id"]
        if nid not in self.nodes:
            return {"ok": False}
        await self._node_died(nid)
        return {"ok": True}

    async def _h_drain_node(self, conn, a):
        """Mark a node unschedulable (autoscaler scale-down handshake;
        reference DrainNode, gcs_node_manager). Running work is untouched;
        the caller re-checks idleness before terminating."""
        node = self.nodes.get(a["node_id"])
        if node is None:
            return {"ok": False}
        node.draining = bool(a.get("on", True))
        return {"ok": True}

    async def _h_resource_demand(self, conn, a):
        """Aggregate unmet resource demand (reference autoscaler v2's
        ClusterStatus demand summary, autoscaler/v2/autoscaler.py:42): the
        resource shapes of queued tasks/actor creations plus the bundles of
        placement groups that could not be placed. Drives scale-up."""
        unit = CONFIG.resource_unit
        demands: list[dict] = []
        for spec in self.pending:
            demands.append({k: v / unit for k, v in (spec.resources or {}).items()})
        for ent in self.actors.values():
            if ent.state == "PENDING" and not ent.resources_held:
                demands.append({k: v / unit
                                for k, v in (ent.spec.resources or {}).items()})
        pg_demands: list[dict] = []
        for pg in self.pgs.values():
            if pg.get("state") == "PENDING":
                for raw in pg.get("bundles_raw", []):
                    pg_demands.append({k: v / unit for k, v in raw.items()})
        return {"demand": demands, "pg_demand": pg_demands}

    async def _h_object_store_stats(self, conn, a):
        """Cluster shm usage (backs the Data executor's resource-based
        backpressure; reference streaming_executor_state's
        object-store-memory policy). Usage comes from node-agent heartbeats
        — the stores' own accounting — NOT the object directory, whose
        entries stay 'live' after a block spills to disk (directory-based
        counting latched backpressure on permanently)."""
        shm = sum(n.shm_used for n in self.nodes.values() if n.alive)
        n_nodes = max(1, sum(1 for n in self.nodes.values() if n.alive))
        return {"shm_bytes": shm,
                "capacity": n_nodes * CONFIG.object_store_memory_bytes}

    async def _h_cluster_resources(self, conn, a):
        total: dict[str, float] = {}
        avail: dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.total.to_dict().items():
                total[k] = total.get(k, 0) + v
            for k, v in n.available.to_dict().items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def _h_state_snapshot(self, conn, a):
        # Job driver subprocesses consume no scheduler-visible resources, so
        # a node hosting one looks fully idle; surface the count so the
        # autoscaler never drains a node out from under a running driver.
        jobs_per_node: dict = {}
        for job in self.jobs.values():
            if job["status"] in ("PENDING", "RUNNING"):
                jn = job["node_id"]
                jobs_per_node[jn] = jobs_per_node.get(jn, 0) + 1
        return {
            "nodes": {
                nid: {
                    "alive": n.alive,
                    "liveness": n.liveness,
                    "incarnation": n.incarnation,
                    "address": n.address,
                    "total": n.total.to_dict(),
                    "available": n.available.to_dict(),
                    "labels": n.labels,
                    "active_jobs": jobs_per_node.get(nid, 0),
                    # Heartbeat freshness: consumers that must not trust a
                    # dead-but-undetected node (elastic sizing) filter on it.
                    "beat_age": time.monotonic() - n.last_beat,
                }
                for nid, n in self.nodes.items()
            },
            "actors": {
                aid: {
                    "state": e.state,
                    "name": e.name,
                    "node_id": e.node_id,
                    "class": e.spec.name,
                    "restarts_used": e.restarts_used,
                }
                for aid, e in self.actors.items()
            },
            "pending_tasks": len(self.pending),
            "dispatched_tasks": len(self.dispatched),
            "num_objects": len(self.objects),
            "pgs": {pid: {"state": p["state"], "strategy": p["strategy"]} for pid, p in self.pgs.items()},
        }

    async def _h_worker_stacks(self, conn, a):
        """Route a live stack-dump request to the agent hosting the worker
        (reference: dashboard -> reporter agent py-spy)."""
        nid = a.get("node_id")
        if nid is None:
            hits = self._find_worker_nodes(a["worker_id"])
            if len(hits) > 1:
                return {"found": False,
                        "stacks": f"worker id prefix "
                                  f"{a['worker_id'][:12]!r} is ambiguous "
                                  f"({len(hits)} nodes match) — use a "
                                  f"longer prefix"}
            if not hits:
                return {"found": False,
                        "stacks": f"worker {a['worker_id'][:12]} not found "
                                  f"in the actor, lease, or dispatch tables"}
            nid = next(iter(hits))
        nconn = self.node_conns.get(nid)
        if nconn is None or nconn.closed:
            return {"found": False, "stacks": "node not found"}
        return await nconn.call("worker_stacks", worker_id=a["worker_id"],
                                _timeout=10)

    async def _h_ping(self, conn, a):
        return {"pong": True, "session_id": self.session_id}
