"""Serialization with zero-copy buffer support.

Parity target: reference python/ray/_private/serialization.py
(SerializationContext:122, serialize:544) — cloudpickle + pickle protocol 5
out-of-band buffers so numpy/jax arrays are not copied into the pickle stream.

Wire format of a serialized object:
    header: pickle5 stream (with buffer placeholders)
    buffers: list of raw memoryviews (concatenated on the wire, lengths in meta)

ObjectRefs embedded in a value are swapped for `_RefPlaceholder` during
serialization and re-hydrated on deserialization, with the set of contained
refs reported to the caller (needed for borrowed-ref tracking, cf. reference
ReferenceCounter borrower protocol reference_count.h:72).
"""

from __future__ import annotations

import io
import pickle
import struct
from dataclasses import dataclass

import cloudpickle


@dataclass
class SerializedObject:
    header: bytes
    buffers: list  # list of bytes-like (memoryview/bytes)
    contained_refs: list  # list of ObjectRef

    def total_bytes(self) -> int:
        return len(self.header) + sum(len(b) for b in self.buffers)

    def to_bytes(self) -> bytes:
        """Flatten to a single contiguous blob (for inline/wire payloads).
        Layout: [4B nrefs][nrefs * (2B len + oid hex)] [4B nbufs][8B hlen]
        [header][ (8B len, raw)* ]. Contained refs are stored by id so a
        deserializer in another process can re-hydrate borrowed ObjectRefs.
        Single source of truth for the layout is to_parts()."""
        if not self.buffers and not self.contained_refs:
            # Tiny-result fast path (every scalar actor/task return):
            # [nrefs=0][nbufs=0][hlen][header] in one concat.
            return struct.pack("<IIQ", 0, 0, len(self.header)) + self.header
        return b"".join(
            p if isinstance(p, (bytes, bytearray)) else bytes(p)
            for p in self.to_parts())

    def to_parts_meta(self) -> bytes:
        """The fixed-size prefix of the wire layout (ref table + counts +
        header length) — the single source of truth shared by to_parts()
        and the store's serialize-into-shm put_serialized()."""
        ref_oids = [r.hex() if hasattr(r, "hex") else r for r in self.contained_refs]
        meta = [struct.pack("<I", len(ref_oids))]
        for h in ref_oids:
            hb = h.encode()
            meta.append(struct.pack("<H", len(hb)))
            meta.append(hb)
        meta.append(struct.pack("<I", len(self.buffers)))
        meta.append(struct.pack("<Q", len(self.header)))
        return b"".join(meta)

    def to_parts(self) -> list:
        """Same byte stream as to_bytes() but as a list of parts, so the shm
        store can write each raw buffer straight into the mmap — one copy
        total on the put path (reference plasma writes once into shm;
        round-1 joined everything first = two extra full copies)."""
        parts = [self.to_parts_meta(), self.header]
        for b in self.buffers:
            parts.append(struct.pack("<Q", len(b)))
            parts.append(b)
        return parts

    @staticmethod
    def from_buffer(buf) -> "SerializedObject":
        """Zero-copy parse from a contiguous blob (memoryview over shm).
        `contained_refs` comes back as a list of oid hex strings."""
        mv = memoryview(buf)
        (nrefs,) = struct.unpack_from("<I", mv, 0)
        off = 4
        ref_oids = []
        for _ in range(nrefs):
            (rlen,) = struct.unpack_from("<H", mv, off)
            off += 2
            ref_oids.append(bytes(mv[off : off + rlen]).decode())
            off += rlen
        (nbufs,) = struct.unpack_from("<I", mv, off)
        off += 4
        (hlen,) = struct.unpack_from("<Q", mv, off)
        off += 8
        header = bytes(mv[off : off + hlen])
        off += hlen
        buffers = []
        for _ in range(nbufs):
            (blen,) = struct.unpack_from("<Q", mv, off)
            off += 8
            buffers.append(mv[off : off + blen])  # zero-copy slice
            off += blen
        return SerializedObject(header=header, buffers=buffers, contained_refs=ref_oids)


def inline_header_blob(header: bytes) -> bytes:
    """Wrap a bare pickle-5 header in the standard inline wire layout
    ([nrefs=0][nbufs=0][hlen][header], the to_bytes() tiny-result shape).
    Used to inline DEVICE-REF PLACEHOLDERS (_private/device_store._DeviceRef)
    in args/returns: the placeholder rides every existing blob path —
    including the no-refs/no-bufs fast deserialize — and unpickling it
    resolves the array through the device plane's tier ladder."""
    return struct.pack("<IIQ", 0, 0, len(header)) + header


class _RefPlaceholder:
    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


class _RefPickler(cloudpickle.Pickler):
    """cloudpickle pickler that swaps ObjectRefs for persistent ids."""

    def __init__(self, f, ref_class, contained_refs, **kw):
        super().__init__(f, **kw)
        self._ref_class = ref_class
        self._contained_refs = contained_refs

    def persistent_id(self, obj):  # noqa: N802
        if isinstance(obj, self._ref_class):
            self._contained_refs.append(obj)
            return ("rt_ref", len(self._contained_refs) - 1)
        return None


class _RefUnpickler(pickle.Unpickler):
    def __init__(self, f, resolve_ref, **kw):
        super().__init__(f, **kw)
        self._resolve_ref = resolve_ref

    def persistent_load(self, pid):  # noqa: N802
        tag, idx = pid
        if tag == "rt_ref" and self._resolve_ref is not None:
            return self._resolve_ref(idx)
        raise pickle.UnpicklingError(f"unknown persistent id {pid}")


# Exact types that can never contain an ObjectRef (or an oob buffer):
# results of this shape skip the cloudpickle ref-scanning pickler entirely —
# the dominant case for actor-method replies (None / status scalars).
_ATOMIC_TYPES = (type(None), bool, int, float)


def serialize(value, ref_class=None) -> SerializedObject:
    t = type(value)
    if t in _ATOMIC_TYPES or (t in (str, bytes) and len(value) < 4096):
        return SerializedObject(
            header=pickle.dumps(value, protocol=5), buffers=[], contained_refs=[])

    buffers: list = []
    contained_refs: list = []

    def buffer_callback(pb: pickle.PickleBuffer):
        buffers.append(pb.raw())
        return False  # out-of-band

    if ref_class is not None:
        f = io.BytesIO()
        p = _RefPickler(f, ref_class, contained_refs, protocol=5,
                        buffer_callback=buffer_callback)
        p.dump(value)
        header = f.getvalue()
    else:
        header = cloudpickle.dumps(value, protocol=5, buffer_callback=buffer_callback)
    return SerializedObject(header=header, buffers=buffers, contained_refs=contained_refs)


def deserialize(sobj: SerializedObject, resolve_ref=None):
    """resolve_ref(index) -> ObjectRef for persistent-id re-hydration."""
    if not sobj.contained_refs:
        # No persistent ids in the stream: C-level loads, no Unpickler object.
        return pickle.loads(sobj.header, buffers=sobj.buffers)
    up = _RefUnpickler(io.BytesIO(sobj.header), resolve_ref, buffers=sobj.buffers)
    return up.load()


def dumps_oob(value) -> tuple[bytes, list]:
    """Plain pickle5 dump with out-of-band buffers (no ref tracking).

    Uses stdlib pickle (much faster than cloudpickle on this hot path — every
    RPC frame goes through here); RPC payloads only contain importable types
    (TaskSpec, primitives, bytes). User functions/closures go through
    serialize() above, which keeps the cloudpickle pickler. Falls back to
    cloudpickle for the rare unpicklable-by-reference value (e.g. a user
    exception instance embedded in an error blob)."""
    buffers: list = []
    cb = lambda pb: (buffers.append(pb.raw()), False)[1]  # noqa: E731
    try:
        header = pickle.dumps(value, protocol=5, buffer_callback=cb)
    except Exception:
        buffers.clear()
        header = cloudpickle.dumps(value, protocol=5, buffer_callback=cb)
    return header, buffers


def loads_oob(header: bytes, buffers: list):
    return pickle.loads(header, buffers=buffers)
