"""Task specification — the unit handed from submitter to scheduler to worker.

Parity target: reference src/ray/common/task/task_spec.h (TaskSpecification)
+ python/ray/includes/function_descriptor.pxi. Functions are registered once
in the controller KV by id and referenced by hash (cf. reference
python/ray/_private/function_manager.py export/import via GCS KV).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, ClassVar, Optional

NORMAL = "normal"
ACTOR_CREATE = "actor_create"
ACTOR_TASK = "actor_task"

#: num_returns value for streaming-generator tasks (reference
#: num_returns="streaming" -> ObjectRefGenerator).
STREAMING = "streaming"

#: Arg wire-encoding tag for device-plane arrays: ("dref", oid,
#: placeholder_blob). The placeholder (see _private/device_store) carries
#: the producer's device-location hint INSIDE the spec, so the executor
#: resolves it peer-to-peer with no controller round trip — the device
#: edition of the ("ref", oid) encoding below.
DEVICE_REF = "dref"


@dataclass
class SchedulingStrategy:
    """DEFAULT (hybrid pack/spread), SPREAD, node affinity, or placement group.

    Parity: reference python/ray/util/scheduling_strategies.py +
    raylet/scheduling/policy/*."""

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id: Optional[str] = None
    soft: bool = False
    pg_id: Optional[str] = None
    pg_bundle_index: int = -1
    pg_capture_child_tasks: bool = False

    # Tuple state instead of the default instance-dict pickle: strategy rides
    # in every task frame, and field names in the stream cost real CPU on the
    # 2-4 hops a spec makes (cf. reference: TaskSpecification is a protobuf).
    def __getstate__(self):
        return (self.kind, self.node_id, self.soft, self.pg_id,
                self.pg_bundle_index, self.pg_capture_child_tasks)

    def __setstate__(self, s):
        (self.kind, self.node_id, self.soft, self.pg_id,
         self.pg_bundle_index, self.pg_capture_child_tasks) = s


@dataclass
class TaskSpec:
    task_id: str
    kind: str  # NORMAL | ACTOR_CREATE | ACTOR_TASK
    name: str
    # Function: registered blob id in controller KV ("fn:<id>") — workers cache.
    function_id: str
    method_name: str = ""  # for actor tasks
    # Encoded args: list of ("v", header, [bufs]) or ("ref", oid, owner_addr)
    args: list = field(default_factory=list)
    kwargs: dict = field(default_factory=dict)
    num_returns: int = 1
    resources: dict = field(default_factory=dict)  # raw fixed-point mapping
    strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    max_retries: int = 3
    retry_exceptions: bool = False
    runtime_env: dict = field(default_factory=dict)
    # Ownership (cf. reference core_worker TaskManager/ReferenceCounter):
    owner_id: str = ""  # worker id of submitter
    owner_addr: Optional[tuple] = None  # (host, port) of owner's RPC server
    # Actor linkage:
    actor_id: Optional[str] = None
    max_restarts: int = 0
    max_task_retries: int = 0
    max_concurrency: int = 1
    actor_name: Optional[str] = None
    namespace: str = "default"
    get_if_exists: bool = False
    #: "detached" = survives its owner (reference actor lifetime); None =
    #: dies with the owner (fate-sharing) and is never persisted.
    lifetime: Optional[str] = None
    # retry bookkeeping (mutated by controller):
    attempt: int = 0
    #: Actor concurrency groups: {group_name: max_concurrency} (reference
    #: concurrency_group_manager.h); methods opt in via @ray_tpu.method.
    concurrency_groups: Optional[dict] = None
    #: Per-attempt execution deadline (@remote(timeout_s=...)), enforced
    #: worker-side: an attempt running longer is interrupted and fails as a
    #: retryable TaskTimeoutError (system failure under max_retries).
    timeout_s: Optional[float] = None
    #: Trace context (trace_id, parent_span_id) from the tracing plane
    #: (README "Tracing & timeline"): set at submit when the root sampled,
    #: carried across retries AND across the direct->controller failover
    #: re-route so every attempt's execute span chains to one trace. None
    #: (tracing off / unsampled) keeps every wire format at its pre-tracing
    #: arity — the off path is byte-identical.
    trace: Optional[tuple] = None

    def __getstate__(self):
        if self.trace is None:
            # Traceless specs keep the 26-field state: byte-identical wire/
            # snapshot bytes with RT_TRACING unset (pinned by test).
            return (self.task_id, self.kind, self.name, self.function_id,
                    self.method_name, self.args, self.kwargs,
                    self.num_returns, self.resources, self.strategy,
                    self.max_retries, self.retry_exceptions,
                    self.runtime_env, self.owner_id, self.owner_addr,
                    self.actor_id, self.max_restarts, self.max_task_retries,
                    self.max_concurrency, self.actor_name, self.namespace,
                    self.get_if_exists, self.lifetime, self.attempt,
                    self.concurrency_groups, self.timeout_s)
        return (self.task_id, self.kind, self.name, self.function_id,
                self.method_name, self.args, self.kwargs, self.num_returns,
                self.resources, self.strategy, self.max_retries,
                self.retry_exceptions, self.runtime_env, self.owner_id,
                self.owner_addr, self.actor_id, self.max_restarts,
                self.max_task_retries, self.max_concurrency, self.actor_name,
                self.namespace, self.get_if_exists, self.lifetime,
                self.attempt, self.concurrency_groups, self.timeout_s,
                self.trace)

    def __setstate__(self, s):
        if len(s) == 23:  # pre-'lifetime' snapshots: insert None before attempt
            s = s[:22] + (None,) + s[22:]
        if len(s) == 24:  # pre-'concurrency_groups' snapshots
            s = s + (None,)
        if len(s) == 25:  # pre-'timeout_s' snapshots
            s = s + (None,)
        if len(s) == 26:  # pre-'trace' snapshots (and traceless specs)
            s = s + (None,)
        (self.task_id, self.kind, self.name, self.function_id,
         self.method_name, self.args, self.kwargs, self.num_returns,
         self.resources, self.strategy, self.max_retries,
         self.retry_exceptions, self.runtime_env, self.owner_id,
         self.owner_addr, self.actor_id, self.max_restarts,
         self.max_task_retries, self.max_concurrency, self.actor_name,
         self.namespace, self.get_if_exists, self.lifetime,
         self.attempt, self.concurrency_groups, self.timeout_s,
         self.trace) = s

    def clone(self) -> "TaskSpec":
        """Shallow copy with its own SchedulingStrategy. The controller
        mutates specs it accepts (attempt, max_retries, pg_bundle_index);
        over the in-process transport the submitter's live object arrives, so
        ingestion points clone to keep owner-side state (lineage specs,
        shared strategy objects) isolated."""
        new = object.__new__(TaskSpec)
        new.__setstate__(self.__getstate__())
        s = self.strategy
        ns = object.__new__(SchedulingStrategy)
        ns.__setstate__(s.__getstate__())
        new.strategy = ns
        return new

    # Strategy shared by every actor-call spec: actor tasks never visit the
    # scheduler (they ride the actor pipe straight to the bound worker), so
    # nothing ever mutates it.
    _ACTOR_CALL_STRATEGY: ClassVar["SchedulingStrategy"] = None  # set below

    @classmethod
    def for_actor_call(cls, task_id: str, method_name: str, args, kwargs,
                       num_returns: int, name: str, owner_id: str,
                       owner_addr, actor_id: str, attempt: int = 0,
                       trace: Optional[tuple] = None) -> "TaskSpec":
        """Cheap constructor for the actor hot path: skips dataclass default
        factories (~3us/call at n:n rates) and shares one strategy object."""
        sp = object.__new__(cls)
        sp.task_id = task_id
        sp.kind = ACTOR_TASK
        sp.name = name
        sp.function_id = ""
        sp.method_name = method_name
        sp.args = args
        sp.kwargs = kwargs
        sp.num_returns = num_returns
        sp.resources = {}
        sp.strategy = cls._ACTOR_CALL_STRATEGY
        sp.max_retries = 0
        sp.retry_exceptions = False
        sp.runtime_env = {}
        sp.owner_id = owner_id
        sp.owner_addr = owner_addr
        sp.actor_id = actor_id
        sp.max_restarts = 0
        sp.max_task_retries = 0
        sp.max_concurrency = 1
        sp.actor_name = None
        sp.namespace = "default"
        sp.get_if_exists = False
        sp.lifetime = None
        sp.attempt = attempt
        sp.concurrency_groups = None
        sp.timeout_s = None
        sp.trace = trace
        return sp

    _NORMAL_CALL_STRATEGY: ClassVar["SchedulingStrategy"] = None  # set below

    def task_call_tuple(self) -> tuple:
        """Compact wire record for direct-path `exec_tasks` frames (the
        owner-side leased dispatch): frame-constant fields — owner, the
        class's resources/strategy — ride once per frame; the full 24-field
        spec pickle costs ~3x this on encode+decode at direct-dispatch
        rates. Executor-side counterpart: `leased_task_spec`. The trailing
        trace context rides ONLY when sampled — traceless records keep the
        11-field pre-tracing arity (byte-identical off, pinned by test)."""
        call = (self.task_id, self.function_id, self.name, self.args,  # rtcheck: wire=exec_tasks.call
                self.kwargs, self.num_returns, self.max_retries,
                self.retry_exceptions, self.runtime_env or None, self.attempt,
                self.timeout_s, self.trace)
        return call if self.trace is not None else call[:11]

    @classmethod
    def for_normal_call(cls, call: tuple, owner_id: str, owner_addr,
                        resources: dict) -> "TaskSpec":
        """Rebuild an executor-side NORMAL spec from a `task_call_tuple`
        wire record (cheap constructor, same shape as for_actor_call)."""
        if len(call) == 10:  # pre-'timeout_s' wire records
            call = call + (None,)
        if len(call) == 11:  # traceless records (and pre-'trace' senders)
            call = call + (None,)
        (task_id, function_id, name, args, kwargs, num_returns, max_retries,  # rtcheck: wire=exec_tasks.call
         retry_exceptions, runtime_env, attempt, timeout_s, trace) = call
        sp = object.__new__(cls)
        sp.task_id = task_id
        sp.kind = NORMAL
        sp.name = name
        sp.function_id = function_id
        sp.method_name = ""
        sp.args = args
        sp.kwargs = kwargs
        sp.num_returns = num_returns
        sp.resources = resources
        # The executor never schedules a leased spec: share one strategy.
        sp.strategy = cls._NORMAL_CALL_STRATEGY
        sp.max_retries = max_retries
        sp.retry_exceptions = retry_exceptions
        sp.runtime_env = runtime_env or {}
        sp.owner_id = owner_id
        sp.owner_addr = owner_addr
        sp.actor_id = None
        sp.max_restarts = 0
        sp.max_task_retries = 0
        sp.max_concurrency = 1
        sp.actor_name = None
        sp.namespace = "default"
        sp.get_if_exists = False
        sp.lifetime = None
        sp.attempt = attempt
        sp.concurrency_groups = None
        sp.timeout_s = timeout_s
        sp.trace = trace
        return sp

    def actor_call_tuple(self) -> tuple:
        """Compact wire record for `actor_calls` frames — the full 24-field
        spec pickle costs ~9us/call encode+decode and 293B; this is ~1/3 of
        both. Frame-constant fields (owner, actor id) ride once per frame.
        The trace context rides only when sampled (see task_call_tuple)."""
        call = (self.task_id, self.method_name, self.args, self.kwargs,  # rtcheck: wire=actor_calls.call
                self.num_returns, self.name, self.attempt, self.trace)
        return call if self.trace is not None else call[:7]

    def ref_arg_oids(self) -> list[str]:
        """Oids of by-reference arguments — the single place that knows the
        ('ref', oid) arg wire encoding (used by locality scheduling and
        executor-side prefetch). DEVICE_REF ('dref') args are deliberately
        excluded: their placeholder already names the producer, so a
        controller-backed prefetch/locality probe would be a wasted round
        trip — resolution pulls peer-to-peer at decode time."""
        out = []
        for a in self.args or ():
            if isinstance(a, (tuple, list)) and a and a[0] == "ref":
                out.append(a[1])
        for a in (self.kwargs or {}).values():
            if isinstance(a, (tuple, list)) and a and a[0] == "ref":
                out.append(a[1])
        return out

    def return_object_ids(self) -> list[str]:
        # Object id hex = task id hex + 4B little-endian return index hex
        # (ids.ObjectID.for_task_return) — derivable by string concat, which
        # matters: this runs once per call on both submitter and executor.
        n = self.num_returns
        if n == 1:
            return [self.task_id + "00000000"]
        if n == STREAMING:
            # Streaming generator (reference core_worker.proto:478
            # ReportGeneratorItemReturns): item oids use indices 0..k-1 as
            # they are yielded; the single declared return is the COMPLETION
            # sentinel at the reserved max index. It resolves to the item
            # count on success (or the stream's error), so every existing
            # submit/retry/cancel/failure path that touches "the task's
            # return ids" drives the generator's end-of-stream for free.
            return [self.task_id + "ffffffff"]
        tid = self.task_id
        return [tid + i.to_bytes(4, "little").hex() for i in range(n)]


TaskSpec._ACTOR_CALL_STRATEGY = SchedulingStrategy()
TaskSpec._NORMAL_CALL_STRATEGY = SchedulingStrategy()


def actor_call_spec(call: tuple, owner_id: str, owner_addr, actor_id: str) -> TaskSpec:
    """Rebuild an executor-side spec from an `actor_calls` wire record."""
    if len(call) == 7:  # traceless records (and pre-'trace' senders)
        call = call + (None,)
    task_id, method_name, args, kwargs, num_returns, name, attempt, trace = call  # rtcheck: wire=actor_calls.call
    return TaskSpec.for_actor_call(
        task_id, method_name, args, kwargs, num_returns, name,
        owner_id, tuple(owner_addr) if owner_addr else None, actor_id,
        attempt=attempt, trace=trace)
