"""Unique identifiers for tasks, objects, actors, nodes, placement groups.

Parity target: reference src/ray/common/id.h + python/ray/includes/unique_ids.pxi.
The reference derives ObjectIDs from (task id, return index) so ownership and
lineage can be recovered from the id alone; we keep that property.
"""

from __future__ import annotations

import os
import threading

_UNIQUE_LEN = 16  # bytes


class _EntropyPool:
    """Buffered os.urandom: one syscall per 4 KiB instead of one per id.
    os.urandom is a full getrandom()/read syscall, and id minting sits on
    the task-submit hot path — at tens of thousands of submissions/s the
    per-id syscall was the single largest submit-side cost in profiles.
    Ids are not secrets; buffered urandom keeps full entropy. Fork-safe:
    the child's pool resets via os.register_at_fork, so a forked process
    can never re-mint the parent's buffered bytes."""

    __slots__ = ("_buf", "_off", "_lock")

    def __init__(self):
        self._buf = b""
        self._off = 0
        self._lock = threading.Lock()

    def take(self, n: int) -> bytes:
        with self._lock:
            off = self._off
            if off + n > len(self._buf):
                self._buf = os.urandom(max(4096, n))
                off = 0
            self._off = off + n
            return self._buf[off : off + n]

    def reset_after_fork(self):
        # Runs in the forked CHILD: another thread may have held _lock at
        # fork time and no longer exists to release it — REPLACE the lock,
        # never acquire it (the child is single-threaded here).
        self._lock = threading.Lock()
        self._buf = b""
        self._off = 0


_ENTROPY = _EntropyPool()
os.register_at_fork(after_in_child=_ENTROPY.reset_after_fork)


def random_id_bytes(n: int = _UNIQUE_LEN) -> bytes:
    return _ENTROPY.take(n)


class BaseID:
    __slots__ = ("_bytes",)
    _NIL: "BaseID"

    def __init__(self, id_bytes: bytes):
        if not isinstance(id_bytes, bytes):
            raise TypeError(f"id must be bytes, got {type(id_bytes)}")
        self._bytes = id_bytes

    @classmethod
    def from_random(cls):
        return cls(random_id_bytes(_UNIQUE_LEN))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _UNIQUE_LEN)

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * len(self._bytes)

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash((type(self).__name__, self._bytes))

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:16]})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ObjectID(BaseID):
    """Object id = task id (16B) + 4B return index, so the producing task is
    recoverable from the id (lineage reconstruction; cf. reference id.h
    ObjectID::ForTaskReturn)."""

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def from_put(cls) -> "ObjectID":
        # Puts have no producing task; index 0xFFFFFFFF marks "put".
        return cls(random_id_bytes(_UNIQUE_LEN)
                   + (0xFFFFFFFF).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:_UNIQUE_LEN])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[_UNIQUE_LEN:], "little")

    def is_put(self) -> bool:
        return self.return_index() == 0xFFFFFFFF

    @classmethod
    def nil(cls):
        return cls(b"\x00" * (_UNIQUE_LEN + 4))


class _Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
