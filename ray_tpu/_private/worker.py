"""Per-process client runtime: ownership, objects, task/actor submission.

Parity target: the reference core worker (src/ray/core_worker/core_worker.h:166)
+ its Python face (python/ray/_private/worker.py): TaskManager (task_manager.h:175,
retries + lineage resubmit cc:313), ReferenceCounter (reference_count.h:72),
in-process memory store (memory_store.h:45), plasma provider
(plasma_store_provider.h:93), direct actor transport
(transport/actor_task_submitter.h:78 — ordered per-caller queues over a direct
worker connection).

Every process (driver and executing workers alike) hosts one `Worker`:
an IO event-loop thread, an RPC server (serves `fetch_object` and, on actor
workers, `actor_call`), a shared-memory LocalStore view, and one connection to
the controller.
"""

from __future__ import annotations

import asyncio
import logging
import pickle
import struct
import threading
import time
import traceback
from collections import deque
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Any, Optional

from ray_tpu import exceptions as exc
from ray_tpu._private import device_store, rpc
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.ids import ObjectID, TaskID, WorkerID
from ray_tpu._private.lease import LeaseManager, _record_dispatch
from ray_tpu._private.object_store import LocalStore
from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.serialization import (
    SerializedObject,
    deserialize,
    dumps_oob,
    loads_oob,
    serialize,
)
from ray_tpu._private.task_spec import (
    ACTOR_CREATE,
    ACTOR_TASK,
    DEVICE_REF,
    NORMAL,
    STREAMING,
    SchedulingStrategy,
    TaskSpec,
)

logger = logging.getLogger(__name__)

_MODE_DRIVER = "driver"
_MODE_WORKER = "worker"


class ObjectRef:
    """A future for an object in the cluster (reference: ObjectRef in
    python/ray/includes/object_ref.pxi; ownership semantics from
    reference_count.h:72 — only the owner process refcounts; deserialized
    copies are BORROWED and pin the object at the controller via the
    borrower protocol (borrow_add/borrow_drop) until dropped)."""

    __slots__ = ("_oid", "_owned", "_worker", "_borrow", "__weakref__")

    def __init__(self, oid: str, owned: bool = False, worker: "Worker" = None,
                 borrow: bool = False):
        self._oid = oid
        self._owned = owned
        self._worker = worker
        self._borrow = False
        if owned and worker is not None:
            worker._incref(oid)
        elif borrow and worker is not None:
            # Registers with the controller (deduped per process); False for
            # oids this process owns anyway.
            self._borrow = worker._borrow_incref(oid)

    def hex(self) -> str:
        return self._oid

    def binary(self) -> bytes:
        return bytes.fromhex(self._oid)

    def task_id(self) -> str:
        return ObjectID.from_hex(self._oid).task_id().hex()

    def __hash__(self):
        return hash(self._oid)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._oid == self._oid

    def __repr__(self):
        return f"ObjectRef({self._oid[:16]})"

    def __del__(self):
        if self._worker is not None:
            try:
                if self._owned:
                    self._worker._decref(self._oid)
                elif self._borrow:
                    self._worker._borrow_decref(self._oid)
            except Exception:
                pass

    def __reduce__(self):
        # Plain-pickle fallback (e.g. a ref captured in a closure): the
        # deserialized copy is a borrowed ref bound to that process's worker.
        return (_borrowed_ref, (self._oid,))

    def future(self):
        """concurrent.futures.Future view of this ref."""
        import concurrent.futures

        f: concurrent.futures.Future = concurrent.futures.Future()

        def _bg():
            try:
                f.set_result(self._worker.get([self])[0] if self._worker else None)
            except Exception as e:
                f.set_exception(e)

        threading.Thread(target=_bg, daemon=True).start()
        return f


def _borrowed_ref(oid: str) -> ObjectRef:
    return ObjectRef(oid, owned=False, worker=global_worker(), borrow=True)


_watchers_lock = threading.Lock()


class _Resolution:
    """Per-object resolution slot.

    The blocking Event is created LAZILY by the first waiter that actually
    has to block: in pipelined/async workloads most results arrive before
    get() looks at them, and a threading.Event costs a Condition + Lock
    allocation — measurable at tens of thousands of calls/s on one core."""

    __slots__ = ("done", "event", "inline", "holders", "error", "watchers")

    def __init__(self):
        self.done = False
        self.event = None  # lazily-created by a blocking waiter
        self.inline = None
        self.holders: list = []
        self.error = None
        self.watchers = None  # lazily-created list of resolve callbacks

    def add_watcher(self, cb) -> bool:
        """Run cb at resolve time, exactly once. Returns False if already
        resolved — the CALLER must then run cb itself. The lock serializes
        against resolve()'s swap so a callback can never be lost or run
        twice."""
        with _watchers_lock:
            if self.done:
                return False
            if self.watchers is None:
                self.watchers = []
            self.watchers.append(cb)
            return True

    def wait(self, timeout=None) -> bool:
        if self.done:
            return True
        with _watchers_lock:
            if self.done:
                return True
            ev = self.event
            if ev is None:
                ev = self.event = threading.Event()
        return ev.wait(timeout)

    def remove_watcher(self, cb):
        """Deregister a watcher added by add_watcher (no-op if it already
        ran or was cleared by resolve)."""
        with _watchers_lock:
            if self.watchers is not None:
                try:
                    self.watchers.remove(cb)
                except ValueError:
                    pass

    def resolve(self, inline, holders, error):
        # Values are published BEFORE done flips; the GIL orders these for
        # readers that check `done` without the lock.
        self.inline = inline
        self.holders = holders or []
        self.error = error
        with _watchers_lock:
            self.done = True
            ev = self.event
            ws, self.watchers = self.watchers, None
        if ev is not None:
            ev.set()
        for cb in ws or ():
            try:
                cb()
            except Exception:
                pass

    def reset(self):
        """Re-arm in place (reconstruction): getters already blocked on
        `event` keep waiting on THIS object, so it must not be replaced."""
        with _watchers_lock:
            self.inline = None
            self.holders = []
            self.error = None
            self.done = False
            if self.event is not None:
                self.event.clear()


class _GenState:
    """Owner-side state of one streaming-generator task (reference
    TaskManager's ObjectRefStream, task_manager.h:175 area). Items arrive as
    `gen_items` pushes on the same ordered connection as the final reply;
    the completion sentinel's resolution (watching it drives finish())
    carries the authoritative item count so a completion that overtakes
    trailing items — or a retry re-reporting earlier indices — cannot
    truncate or duplicate the stream."""

    __slots__ = ("task_id", "cond", "queue", "produced", "consumed", "done",
                 "total", "error", "conn", "ack_stride")

    def __init__(self, task_id: str, ack_stride: int):
        self.task_id = task_id
        self.cond = threading.Condition()
        self.queue: deque = deque()  # oids ready to consume
        self.produced = 0  # next expected item index
        self.consumed = 0
        self.done = False
        self.total: int | None = None  # authoritative count, once known
        self.error = None
        self.conn = None  # connection items arrived on (for acks)
        self.ack_stride = ack_stride

    def finish(self, total: int | None, error):
        with self.cond:
            if self.done:
                return
            if error is not None:
                self.error = error
                # Drain whatever made it here, then raise.
                self.total = self.produced
            else:
                self.total = self.produced if total is None else total
            self.done = True
            self.cond.notify_all()

    def conn_lost(self, error):
        """The connection items were riding died. Items and the completion
        reply ride two independently-flushed batch pushers, so a completion
        (total=N) can be processed while trailing items are still buffered
        executor-side; if the conn then dies those items are gone forever —
        truncate the stream with an error instead of waiting on gs.cond
        for items that can never arrive."""
        with self.cond:
            if self.done and self.error is None and self.total is not None \
                    and self.produced < self.total:
                self.error = error
                self.total = self.produced
                self.cond.notify_all()


class ObjectRefGenerator:
    """Iterator of ObjectRefs from a `num_returns="streaming"` task
    (reference python/ray/_raylet.pyx ObjectRefGenerator). next() blocks
    until the executor reports the next yielded item; the stream ends with
    StopIteration, or raises the task's error after the last good item."""

    def __init__(self, worker: "Worker", task_id: str, completion_ref: "ObjectRef"):
        self._worker = worker
        self._task_id = task_id
        # Holding the completion ref keeps its resolution (and the error
        # path) alive for the generator's lifetime.
        self._completion_ref = completion_ref

    @property
    def task_id(self) -> str:
        return self._task_id

    def completed(self) -> "ObjectRef":
        """Ref that resolves to the item count when the stream finishes
        (or raises the stream's error)."""
        return self._completion_ref

    def __iter__(self):
        return self

    def __next__(self):
        return self._next(None)

    def next(self, timeout: float | None = None):
        """Like __next__ but raises GetTimeoutError after `timeout`."""
        return self._next(timeout)

    def _next(self, timeout: float | None):
        w = self._worker
        gs = w._generators.get(self._task_id)
        if gs is None:
            raise StopIteration
        deadline = None if timeout is None else time.monotonic() + timeout
        need_ack = False
        with gs.cond:
            while True:
                if gs.queue:
                    oid = gs.queue.popleft()
                    gs.consumed += 1
                    need_ack = (gs.ack_stride > 0 and gs.conn is not None
                                and gs.consumed % gs.ack_stride == 0)
                    break
                if gs.done and not gs.queue and (
                        gs.total is None or gs.consumed >= gs.total):
                    w._generators.pop(self._task_id, None)
                    if gs.error is not None:
                        raise w._decode_error(gs.error)
                    raise StopIteration
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    raise exc.GetTimeoutError(
                        f"generator {self._task_id[:12]} timed out")
                gs.cond.wait(rem if rem is not None else 1.0)
        if need_ack:
            try:
                gs.conn.push_threadsafe(
                    "gen_ack", task_id=self._task_id, consumed=gs.consumed)
            except Exception:
                pass
        return ObjectRef(oid, owned=True, worker=w)

    def cancel(self, force: bool = False):
        return self._worker.cancel_task(self._task_id, force)

    def __del__(self):
        try:
            self._worker._gen_destroy(self._task_id)
        except Exception:
            pass

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator cannot be pickled; consume it in the owner "
            "process and pass the yielded ObjectRefs instead.")


_global_worker: Optional["Worker"] = None
_global_lock = threading.Lock()


def global_worker() -> Optional["Worker"]:
    return _global_worker


def set_global_worker(w: Optional["Worker"]):
    global _global_worker
    with _global_lock:
        _global_worker = w


class Worker:
    def __init__(self, mode: str, session_id: str, controller_addr: tuple, node_id: str = "",
                 agent_addr: tuple | None = None, worker_id: str | None = None):
        self.mode = mode
        self.session_id = session_id
        self.controller_addr = controller_addr
        self.agent_addr = agent_addr
        self.node_id = node_id
        self.worker_id = worker_id or WorkerID.from_random().hex()
        self.io = rpc.EventLoopThread(name=f"rt-io-{self.worker_id[:6]}")
        self.server = rpc.RpcServer(self._on_request, self._on_push,
                                    on_close=self._on_server_conn_close)
        self.store = LocalStore(session_id, CONFIG.object_store_memory_bytes,
                                CONFIG.object_spill_dir, CONFIG.shm_dir)
        self.controller: Optional[rpc.Connection] = None
        self.server_addr: tuple = ("", 0)
        # Owned-object bookkeeping (reference ReferenceCounter):
        self._refcounts: dict[str, int] = {}
        self._refcounts_lock = threading.Lock()
        self._free_buf: list[str] = []
        self._free_escaped_buf: list[str] = []
        self._free_scheduled = False
        # Borrowed-ref pins held by this process: oid -> local borrow count.
        # The controller learns only the 0<->1 transitions.
        self._borrows: dict[str, int] = {}
        self._borrows_lock = threading.Lock()
        # Pull admission control (reference pull_manager.h:49).
        self._pull_cv = threading.Condition()
        self._pull_inflight = 0
        # Pubsub fan-in (util/pubsub.Subscriber callbacks).
        self.pubsub_listeners: list = []
        # Direct worker-to-worker collective messages (util/collective ring
        # transport) — set by the collective module when a group inits.
        self.collective_msg_cb = None
        self._escaped: set[str] = set()  # owned oids advertised on escape
        # Oids whose resolution came FROM the controller (queued-path
        # object_ready / object_lost): the controller holds directory state
        # for these, so their free must reach it (see _free fast path).
        self._ctrl_resolved: set[str] = set()
        self._resolutions: dict[str, _Resolution] = {}
        self._inline_cache: dict[str, list] = {}  # oid -> blob parts (small objs)
        # oid -> (expiry, detail): GetTimeoutError enrichment cache so a
        # tight polling loop pays the task_status probe once per window.
        self._status_cache: dict[str, tuple] = {}
        self._lineage: dict[str, TaskSpec] = {}  # return oid -> producing spec
        # Device-ref ARG pins: first-return oid -> dref arg oids whose
        # submit-time hold is dropped when that return ref is freed (the
        # args must outlive the result ref — lineage reconstruction re-runs
        # the spec and re-resolves them — but no longer: holding device
        # memory for the session per distinct array argument would leak).
        self._arg_pins: dict[str, tuple] = {}
        self._registered_fns: set[str] = set()
        self._fn_cache: dict[str, Any] = {}
        import weakref

        # fn -> fid, weakly keyed so dynamically created functions (and any
        # closure state they capture) stay collectible.
        self._fn_id_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
        # Direct actor transport: one ordered, pipelined, frame-coalescing
        # pipe per callee actor (reference ActorTaskSubmitter +
        # sequential_actor_submit_queue.h).
        self._actor_pipes: dict[str, "_ActorPipe"] = {}
        self._actor_info: dict[str, dict] = {}
        self._submit_lock = threading.Lock()
        self._submit_buf: list = []
        self._submit_flushing = False
        # Actor pipes with queued calls awaiting a pump: a same-tick burst
        # across N pipes costs ONE cross-thread loop wakeup, not N (the
        # self-pipe write behind run_coroutine_threadsafe is >100us on
        # some sandboxes — it was ~18% of the n:n driver budget).
        self._pump_pipes: list = []
        self._pipe_pump_scheduled = False
        # Streaming generators owned by this process: task_id -> _GenState.
        self._generators: dict[str, _GenState] = {}
        # Hooks used by worker_proc: consumer acks for generator
        # backpressure, and consumer-side stream abandonment.
        self.gen_ack_handler = None  # def (task_id, consumed)
        self.gen_close_handler = None  # def (task_id)
        # Fires after a successful controller reconnect (worker_proc
        # rebinds its batched pushers to the new connection here).
        self.ctrl_reconnected_handler = None  # def ()
        # Hook used by worker_proc to execute actor calls in-order:
        self.actor_push_handler = None  # def (conn, spec)
        self.actor_batch_handler = None  # def (conn, list[spec]) — one frame
        # Hooks used by worker_proc for the direct (leased) task path:
        self.task_push_handler = None  # def (conn, spec) — enqueue for exec
        self.task_batch_handler = None  # def (conn, list[spec]) — one frame
        self.task_cancel_handler = None  # def (task_id)
        # Fires when an inbound connection to this worker's server closes
        # (worker_proc prunes per-connection reply pushers here).
        self.server_close_handler = None  # def (conn)
        self.lease_mgr = LeaseManager(self)
        self._shutdown = False
        self._reconnecting = False  # single-flight controller reconnect

    # ------------------------------------------------------------ lifecycle
    def connect(self):
        import os as _os

        # Bind on the node's externally-visible host (RT_HOST, set by the
        # node agent from its own --host) so direct worker-to-worker
        # connections — actor calls, leased task pushes, collective rings —
        # work across hosts; loopback only for single-machine defaults.
        bind_host = _os.environ.get("RT_HOST") or "127.0.0.1"

        async def _go():
            await self.server.start(bind_host, 0)
            self.server_addr = (bind_host, self.server.port)
            self.controller = await rpc.connect(
                *self.controller_addr,
                on_push=self._on_ctrl_push,
                on_close=self._on_ctrl_close,
                label="ctrl",
            )
            rep = await self.controller.call(
                "register", kind="client", worker_id=self.worker_id,
                mode=self.mode, address=self.server_addr
            )
            CONFIG.load_snapshot(rep["config"])

        self.io.run(_go(), timeout=CONFIG.connect_timeout_s)
        # Tracing plane: re-resolve RT_TRACING now the cluster snapshot is
        # in (and arm/disarm the rpc frame hook accordingly). The event
        # plane re-resolves the same way (RT_EVENTS_BUFFER=0 via
        # _system_config must reach every process).
        _tracing.refresh()
        from ray_tpu._private import events as _events

        _events.refresh()

    def disconnect(self):
        self._shutdown = True
        # Final metrics/span flush BEFORE tearing anything down: without it
        # a short-lived driver loses up to one flush interval of trailing
        # counters and spans (the flusher refuses to push once _shutdown is
        # set — flush_on_shutdown forces the last batch out and fences it
        # with an acked ping so the controller has processed it).
        import sys as _sys

        _m = _sys.modules.get("ray_tpu.util.metrics")
        if _m is not None:
            try:
                _m.flush_on_shutdown()
            except Exception:
                pass
        try:
            self.lease_mgr.shutdown()
        except Exception:
            pass

        async def _bye():
            await self.server.stop()
            if self.controller is not None:
                await self.controller.close()
            for pipe in self._actor_pipes.values():
                if pipe.conn is not None:
                    await pipe.conn.close()

        try:
            self.io.run(_bye(), timeout=5)
        except Exception:
            pass
        self.io.stop()
        try:
            device_store.on_worker_shutdown()
        except Exception:
            pass
        self.store.shutdown()
        if global_worker() is self:
            set_global_worker(None)

    def _on_server_conn_close(self, conn):
        h = self.server_close_handler
        if h is not None:
            h(conn)

    def _on_ctrl_close(self, conn):
        if self._shutdown:
            return
        # Controller restart FT (reference RayletNotifyGCSRestart): retry
        # the same address and re-register instead of dying — running work
        # (leased pipelines, actor pipes) rides direct connections and
        # keeps flowing throughout the outage.
        asyncio.ensure_future(self._a_ctrl_reconnect())

    async def _a_ctrl_reconnect(self):
        # Single-flight: a failed attempt's abandoned connection fires
        # on_close too, which would otherwise spawn N concurrent loops.
        if self._reconnecting:
            return
        self._reconnecting = True
        try:
            await self._a_ctrl_reconnect_inner()
        finally:
            self._reconnecting = False

    async def _a_ctrl_reconnect_inner(self):
        deadline = time.monotonic() + CONFIG.controller_reconnect_timeout_s
        logger.warning("worker %s: controller connection lost; retrying",
                       self.worker_id[:8])
        while not self._shutdown and time.monotonic() < deadline:
            conn = None
            try:
                conn = await rpc.connect(
                    *self.controller_addr,
                    on_push=self._on_ctrl_push,
                    on_close=self._on_ctrl_close,
                    timeout=5,
                    label="ctrl",
                )
                await conn.call(
                    "register", kind="client", worker_id=self.worker_id,
                    mode=self.mode, address=self.server_addr, _timeout=10)
                self.controller = conn
                # A restarted controller lost the histogram-boundary decls
                # this process registered (they ride ONE record per
                # session): forget the declared set so the next observe of
                # each histogram re-declares to the fresh controller.
                import sys as _sys

                _m = _sys.modules.get("ray_tpu.util.metrics")
                if _m is not None:
                    try:
                        _m._hist_declared.clear()
                    except Exception:
                        pass
                h = self.ctrl_reconnected_handler
                if h is not None:
                    try:
                        h()
                    except Exception:
                        pass
                # Re-assert held leases so the restarted controller can
                # rebuild its resource accounting.
                self.lease_mgr.reassert()
                logger.info("worker %s: re-registered with restarted "
                            "controller", self.worker_id[:8])
                return
            except Exception:
                if conn is not None and not conn.closed:
                    try:
                        await conn.close()  # abandoned half-registration
                    except Exception:
                        pass
                await asyncio.sleep(0.5)
        if self._shutdown:
            return
        if self.mode == _MODE_WORKER:
            import os

            os._exit(1)  # cluster is really gone; workers die with it
        logger.error("driver: controller gone for %.0fs; subsequent "
                     "cluster calls will fail",
                     CONFIG.controller_reconnect_timeout_s)

    # --------------------------------------------------------- RPC handlers
    async def _on_request(self, conn, method, a):
        if method == "fetch_object":
            mv = self.store.get(a["oid"])
            if mv is None:
                parts = self._inline_cache.get(a["oid"])
                if parts is None:
                    return {"found": False}
                mv = memoryview(parts[0]) if len(parts) == 1 else \
                    memoryview(b"".join(bytes(p) for p in parts))
            off = a.get("offset")
            if off is None:
                return {"found": True, "data": mv, "size": len(mv)}
            # Chunked read (reference object transfer is chunked,
            # object_manager.h Push/Pull): a zero-copy slice of the shm view
            # rides the wire; the fetcher reassembles into its own segment.
            return {"found": True, "size": len(mv),
                    "data": mv[off : off + a["length"]]}
        if method == "export_device_object":
            # Device object plane tier-1/2 serving side: materialize the
            # pinned array's bytes into the local shm store (one host copy,
            # off the IO loop — a 64MB export must not stall frame
            # processing) so the consumer can attach or stream-fetch.
            found = await asyncio.to_thread(
                device_store.export_to_store, a["oid"], self.store)
            return {"found": bool(found)}
        if method == "health":
            return {"ok": True}
        if method == "whoami":
            # Peer-identity handshake: (host, port) is ambiguous across
            # worker generations (a new worker can reuse a dead worker's
            # ephemeral port), so direct-connection holders verify the
            # worker id before trusting the link.
            return {"worker_id": self.worker_id}
        raise rpc.RpcError(f"worker: unknown method {method}")

    async def _on_push(self, conn, method, a):
        # Direct (leased) task path: owners stream specs straight to this
        # worker's server (reference PushNormalTask, core_worker.proto:462).
        if method == "exec_tasks":
            specs = a.get("specs")
            if specs is None:  # compact form (TaskSpec.task_call_tuple)
                owner_id, owner_addr, resources = a["common"]
                owner_addr = tuple(owner_addr) if owner_addr else None
                specs = [
                    TaskSpec.for_normal_call(c, owner_id, owner_addr,
                                             resources)
                    for c in a["calls"]]
            if self.task_batch_handler is not None:
                # Whole frame as ONE exec-queue item (same shape as the
                # actor_calls path): per-spec queue put/get + condition
                # notify was a measurable slice of a leased worker's core
                # budget at direct-dispatch rates.
                self.task_batch_handler(conn, specs)
            elif self.task_push_handler is not None:
                for spec in specs:
                    self.task_push_handler(conn, spec)
        elif method == "actor_calls":
            if self.actor_batch_handler is not None:
                owner_id, owner_addr, actor_id = a["common"]
                owner_addr = tuple(owner_addr) if owner_addr else None
                self.actor_batch_handler(conn, [
                    TaskSpec.for_actor_call(
                        c[0], c[1], c[2], c[3], c[4], c[5],
                        owner_id, owner_addr, actor_id, attempt=c[6],
                        trace=(c[7] if len(c) > 7 else None))
                    for c in a["calls"]])
        elif method == "actor_tasks":  # full-spec form (compat)
            if self.actor_push_handler is not None:
                for spec in a["specs"]:
                    self.actor_push_handler(conn, spec)
        elif method == "cancel":
            if self.task_cancel_handler is not None:
                self.task_cancel_handler(a["task_id"])
        elif method == "gen_ack":
            h = self.gen_ack_handler
            if h is not None:
                h(a["task_id"], a["consumed"])
        elif method == "gen_close":
            h = self.gen_close_handler
            if h is not None:
                h(a["task_id"])
        elif method == "col_msg":
            cb = self.collective_msg_cb
            if cb is not None:
                cb(a)

    async def _on_ctrl_push(self, conn, method, a):
        if method == "pubsub":
            for cb in list(self.pubsub_listeners):
                try:
                    cb(a["channel"], a["payload"])
                except Exception:
                    pass
        elif method == "device_free":
            # Targeted unpin from the controller: the last reference to
            # device objects THIS process produced died (README "Device
            # objects" ownership). Export segments go with the pin.
            device_store.free_local(a["oids"], self.store)
        elif method == "lease_invalid":
            self.lease_mgr.on_lease_invalid(a["lease_id"], cause=a.get("cause"))
        elif method == "need_resources":
            self.lease_mgr.on_need_resources()
        elif method == "objects_ready":
            # Batched completion notifications: one frame resolves a whole
            # burst of owned oids.
            for item in a["items"]:
                self._apply_object_ready(item)
        elif method == "object_ready":  # single-oid form (compat)
            self._apply_object_ready(a)
        elif method == "worker_log":
            # Streamed worker stdout/stderr (reference log_monitor ->
            # driver printer, "(pid=...) ..." prefixes).
            import sys as _sys

            prefix = f"({a.get('pid')}, {a.get('node_id', '')[:8]})"
            for line in a.get("lines", []):
                print(f"{prefix} {line}", file=_sys.stderr)
        elif method == "object_lost":
            # All copies died with a node. Reconstruct from lineage if we can
            # (reference object_recovery_manager.cc:26), else fail waiters.
            oid = a["oid"]
            self._ctrl_resolved.add(oid)
            if not self._maybe_reconstruct_async(oid):
                msg = a.get("message") or f"object {oid[:16]} lost (node died)"
                h, bufs = dumps_oob({"type": "ObjectLostError",
                                     "message": msg})
                res = self._resolutions.setdefault(oid, _Resolution())
                res.resolve(None, [], [h, *bufs])

    def _apply_object_ready(self, a: dict):
        self._ctrl_resolved.add(a["oid"])
        res = self._resolutions.setdefault(a["oid"], _Resolution())
        res.resolve(a.get("inline"),
                    [tuple(h) for h in a.get("holders", [])], a.get("error"))

    # ----------------------------------------------------------- refcounts
    def _incref(self, oid: str):
        with self._refcounts_lock:
            self._refcounts[oid] = self._refcounts.get(oid, 0) + 1

    def _decref(self, oid: str):
        if self._shutdown:
            return
        free = False
        with self._refcounts_lock:
            n = self._refcounts.get(oid, 0) - 1
            if n <= 0:
                self._refcounts.pop(oid, None)
                free = True
            else:
                self._refcounts[oid] = n
        if free:
            self._free([oid])

    def _borrow_incref(self, oid: str) -> bool:
        """Register this process as a borrower of an oid it does not own.
        Returns True iff a borrow pin was actually taken (the matching
        __del__ must then drop it)."""
        if oid in self._resolutions or self._shutdown:
            return False  # our own object round-tripping back — not a borrow
        # The push happens UNDER the lock: add/drop frames must reach the
        # (ordered) controller connection in the same order as the local
        # 0<->1 transitions, or a drop can cancel a newer add.
        with self._borrows_lock:
            c = self._borrows.get(oid, 0)
            self._borrows[oid] = c + 1
            if c == 0:
                try:
                    self.controller.push_threadsafe(
                        "borrow_add", oid=oid, worker_id=self.worker_id)
                except Exception:
                    pass
        return True

    def _borrow_decref(self, oid: str):
        if self._shutdown:
            return
        with self._borrows_lock:
            c = self._borrows.get(oid, 0) - 1
            if c <= 0:
                self._borrows.pop(oid, None)
                try:
                    self.controller.push_threadsafe(
                        "borrow_drop", oid=oid, worker_id=self.worker_id)
                except Exception:
                    pass
            else:
                self._borrows[oid] = c

    def _free(self, oids: list[str]):
        remote: list[str] = []
        escaped_oids: list[str] = []
        released_args: list[str] = []
        for oid in oids:
            pins = self._arg_pins.pop(oid, None)
            if pins:
                # Result ref died: its task's device-arg pins die with it
                # (decref'd after the loop — a drop to zero re-enters
                # _free for the arg oid).
                released_args.extend(pins)
            self._inline_cache.pop(oid, None)
            escaped = oid in self._escaped
            ctrl = oid in self._ctrl_resolved
            if ctrl:
                self._ctrl_resolved.discard(oid)
            if escaped:
                self._escaped.discard(oid)
                res = self._resolutions.get(oid)
                if res is None or res.done or not res.add_watcher(
                        lambda o=oid: self._resolutions.pop(o, None)):
                    # Resolved (possibly between the check and add_watcher —
                    # registration failing means resolve already ran): the
                    # escape advertise has fired, pop now.
                    # Unresolved: the add_watcher above keeps the resolution
                    # until the producing task finishes, so the escape
                    # advertise can still reach the controller; watchers run
                    # in registration order, advertise before this pop.
                    self._resolutions.pop(oid, None)
                self._lineage.pop(oid, None)
                escaped_oids.append(oid)
                remote.append(oid)
                continue
            res = self._resolutions.get(oid)
            self._lineage.pop(oid, None)
            if (res is not None and not res.done and res.add_watcher(
                    lambda o=oid: self._resolutions.pop(o, None))):
                # Freed BEFORE the producing task completed (fire-and-forget
                # result ref dropped immediately): the reply must still
                # resolve THIS resolution object — completion watchers
                # (device-arg unpins, escape advertises) hang off it — so
                # keep it in the map until resolve pops it.
                res = None
            else:
                self._resolutions.pop(oid, None)
            # Purely-local object: resolved from a direct (lease/actor-pipe)
            # reply inline, never escaped this process, controller never
            # heard of it — its free is a no-op everywhere else, so don't
            # spend a controller frame + tombstone on it. This is the common
            # case for every small task/actor return consumed by its owner.
            if (not ctrl and res is not None and res.done
                    and not res.holders):
                continue
            # Device-plane pin produced by THIS process (driver put / dref
            # arg): drop it now rather than waiting for the controller's
            # device_free round trip. Escaped device oids skipped above
            # keep their pin while borrowers may still fetch (the grace
            # sweep's targeted device_free lands here via _on_ctrl_push).
            # has_pins() keeps the common host-path free at zero extra cost.
            if device_store.has_pins():
                device_store.free_local([oid])
            self.store.delete(oid)
            remote.append(oid)
        for o in released_args:
            self._decref(o)
        if not remote:
            return
        oids = remote
        # Batch the controller notification: refs die one at a time (GC),
        # but a burst of dying refs (the common teardown of a get() over
        # many results) must not cost one controller frame each.
        with self._refcounts_lock:
            self._free_buf.extend(oids)
            self._free_escaped_buf.extend(escaped_oids)
            need = not self._free_scheduled
            self._free_scheduled = True
        if need:
            try:
                self.io.spawn(self._a_flush_free())
            except Exception:
                # Un-wedge: the next free must be able to reschedule the
                # flush or the controller never hears about any of them.
                with self._refcounts_lock:
                    self._free_scheduled = False

    async def _a_flush_free(self):
        await asyncio.sleep(0.002)  # coalesce the burst
        with self._refcounts_lock:
            oids, self._free_buf = self._free_buf, []
            escaped, self._free_escaped_buf = self._free_escaped_buf, []
            self._free_scheduled = False
        if oids and not self._shutdown:
            try:
                await self.controller.push("free_objects", oids=oids,
                                           escaped=escaped)
            except Exception:
                pass

    # ----------------------------------------------------------------- put
    def put(self, value) -> ObjectRef:
        if isinstance(value, ObjectRef):
            raise TypeError("Calling put() on an ObjectRef is not allowed.")
        if device_store.eligible(value):
            oid, _ = self._put_device(value)
            return ObjectRef(oid, owned=True, worker=self)
        oid = ObjectID.from_put().hex()
        sobj = serialize(value, ref_class=ObjectRef)
        if sobj.contained_refs:  # refs escape into the putted payload
            self._advertise_escaping(
                [r.hex() if isinstance(r, ObjectRef) else r
                 for r in sobj.contained_refs])
        self._store_blob(oid, sobj, register=True)
        return ObjectRef(oid, owned=True, worker=self)

    def _store_blob(self, oid: str, sobj: SerializedObject, register: bool) -> None:
        """Registration is a one-way push: the owner resolves locally, and a
        borrower's wait_object on the controller blocks until the push lands.
        Pushes and later calls share one ordered connection, so a task
        submitted after a put can never be scheduled before the controller
        knows the object (removes one round trip per put — the reference
        plasma Put is similarly fire-and-forget to the owner's local store)."""
        size = sobj.total_bytes()
        if size <= CONFIG.max_inline_object_bytes:
            parts = [sobj.to_bytes()]
            self._inline_cache[oid] = parts
            if register:
                self.controller.push_threadsafe(
                    "register_put", oid=oid, size=size, inline=parts,
                    holder=self.server_addr, owner=self.worker_id)
        else:
            # Serialize-into-shm: the pickle-5 out-of-band buffer views go
            # straight into the destination mmap (no intermediate parts
            # walk; threaded copy per buffer).
            self.store.put_serialized(oid, sobj)
            holder = self.agent_addr or self.server_addr
            if register:
                self.controller.push_threadsafe(
                    "register_put", oid=oid, size=size, inline=None,
                    holder=holder, owner=self.worker_id)
        res = self._resolutions.setdefault(oid, _Resolution())
        res.resolve(None, [self.server_addr], None)

    def _put_device(self, value) -> tuple[str, bytes]:
        """Device-plane put: pin the live array in this process's
        DeviceObjectTable and register only the placeholder with the
        controller (same fire-and-forget ordering argument as _store_blob).
        Returns (oid, placeholder_blob)."""
        oid = ObjectID.from_put().hex()
        blob, nbytes = device_store.pin_put(oid, value, self)
        self.controller.push_threadsafe(
            "register_put", oid=oid, size=nbytes, inline=[blob],
            holder=self.server_addr, owner=self.worker_id,
            **device_store.advert_fields(self.worker_id, self.node_id))
        res = self._resolutions.setdefault(oid, _Resolution())
        res.resolve([blob], [self.server_addr], None)
        return oid, blob

    # ----------------------------------------------------------------- get
    def get(self, refs: list[ObjectRef], timeout: float | None = None) -> list:
        deadline = None if timeout is None else time.monotonic() + timeout
        return [self._get_one(r, deadline) for r in refs]

    def _remaining(self, deadline) -> float | None:
        if deadline is None:
            return None
        rem = deadline - time.monotonic()
        if rem <= 0:
            raise exc.GetTimeoutError("get() timed out")
        return rem

    def _get_one(self, ref: ObjectRef, deadline):
        oid = ref.hex()
        # 1. owned refs: resolved -> straight to materialize (the hot path
        # for harvesting a batch of results); pending -> wait. The local
        # cache/shm probes are skipped either way: an owned object's bytes
        # cannot be locally visible before its resolution lands, and the
        # miss costs a stat per get() racing its producer.
        res = self._resolutions.get(oid)
        if res is not None:
            if not res.done:
                try:
                    rem = self._remaining(deadline)
                except exc.GetTimeoutError:
                    raise self._get_timeout_error(oid) from None
                if not res.wait(timeout=rem):
                    raise self._get_timeout_error(oid)
            return self._materialize(oid, res.inline, res.holders, res.error, deadline)
        # 2. local caches (in-process inline / same-host shm, zero-copy)
        val, found = self._try_local(oid)
        if found:
            return val
        # 3. borrowed refs: ask the controller directly
        rep = self.io.run(self.controller.call(
            "wait_object", oid=oid, timeout=self._remaining(deadline)))
        if rep["status"] == "timeout":
            raise self._get_timeout_error(oid)
        if rep["status"] == "lost":
            raise exc.ObjectLostError(f"object {oid[:16]} lost")
        return self._materialize(oid, rep.get("inline"), [tuple(h) for h in rep.get("holders", [])],
                                 rep.get("error"), deadline)

    def _get_timeout_error(self, oid: str) -> "exc.GetTimeoutError":
        """Enriched get() timeout: name the producing task's CURRENT status
        — queued or running, where, and how long since its last progress
        beacon (the first question a stalled-get user asks). Direct-path
        tasks resolve from this owner's lease tables; everything else (and
        the beacon age) from the controller. Diagnostics only: every lookup
        is best-effort and bounded so enrichment can never hang the error."""
        # Polling loops (`get(ref, timeout=0.05)` in a while) expire this
        # path at high rate: cache the enriched detail per oid for a couple
        # of seconds so the controller round trip below is paid once per
        # window, not once per poll.
        now = time.monotonic()
        cached = self._status_cache.get(oid)
        if cached is not None and cached[0] > now:
            return exc.GetTimeoutError(
                f"get() timed out on {oid[:16]}{cached[1]}")
        detail = ""
        try:
            tid = ObjectID.from_hex(oid).task_id().hex()
            st = self.lease_mgr.task_status(tid) or {}
            if not st.get("found"):
                # Actor calls ride direct pipes: the inflight table is the
                # only place that knows the call is still outstanding.
                for aid, pipe in list(self._actor_pipes.items()):
                    ent = pipe.inflight.get(tid)
                    state = "running"
                    if ent is None:
                        # Not yet pushed (actor still resolving/creating):
                        # the call is parked in the pipe's queue.
                        ent = next((e for e in list(pipe.queue)
                                    if e[0].task_id == tid), None)
                        state = "queued (actor not ready)"
                    if ent is not None:
                        info = self._actor_info.get(aid) or {}
                        st = {"found": True, "state": state,
                              "via": "actor", "name": ent[0].name,
                              "attempt": ent[0].attempt,
                              "node_id": None,
                              "worker_id": info.get("worker_id"),
                              "beacon_age_s": None}
                        break
            ctrl = {}
            try:
                ctrl = self.io.run(self.controller.call(
                    "task_status", task_id=tid, _timeout=1), timeout=2)
            except Exception:
                pass
            if not st.get("found") and ctrl.get("found"):
                st = ctrl
            elif st.get("found") and st.get("beacon_age_s") is None:
                st["beacon_age_s"] = ctrl.get("beacon_age_s")
            if st.get("found"):
                name = st.get("name") or tid[:12]
                where = ""
                if st.get("node_id"):
                    where = f" on node {str(st['node_id'])[:8]}"
                    if st.get("worker_id"):
                        where += f" (worker {str(st['worker_id'])[:8]})"
                via = {"direct": " via direct dispatch",
                       "actor": " as an actor call"}.get(st.get("via"), "")
                beacon = st.get("beacon_age_s")
                if beacon is not None:
                    prog = f"; {beacon:.1f}s since its last progress beacon"
                elif st.get("state") in ("running", "queued"):
                    prog = ("; no progress beacon (stall watchdog idle — "
                            "set RT_STALL_WARN_S to enable)")
                else:
                    prog = ""
                detail = (f": producing task {name!r} (attempt "
                          f"{st.get('attempt')}) is {st.get('state')}"
                          f"{where}{via}{prog}")
            else:
                detail = (f": producing task {tid[:12]} is unknown to the "
                          f"cluster (finished, never submitted, or a put())")
        except Exception:
            detail = ""
        if len(self._status_cache) > 64:
            self._status_cache = {k: v for k, v in self._status_cache.items()
                                  if v[0] > now}
        self._status_cache[oid] = (now + 2.0, detail)
        return exc.GetTimeoutError(f"get() timed out on {oid[:16]}{detail}")

    def _try_local(self, oid: str):
        parts = self._inline_cache.get(oid)
        if parts is not None:
            return self._deserialize_blob(memoryview(parts[0]) if len(parts) == 1 else memoryview(b"".join(bytes(p) for p in parts))), True
        mv = self.store.get(oid)
        if mv is not None:
            return self._deserialize_blob(mv), True
        return None, False

    def _materialize(self, oid: str, inline, holders, error, deadline):
        if error is not None:
            raise self._decode_error(error)
        if inline is not None:
            blob = inline[0] if len(inline) == 1 else b"".join(bytes(p) for p in inline)
            if oid not in self._resolutions:
                # Cache for repeat gets of BORROWED refs only: owned refs
                # re-materialize from their resolution (step 1 of _get_one
                # never consults the cache), so the write was pure churn.
                self._inline_cache[oid] = [blob]
            if deadline is not None:
                # Device-ref placeholders do network work INSIDE the
                # deserialize — bound it by the caller's get() deadline.
                device_store.set_resolve_deadline(deadline)
                try:
                    return self._deserialize_blob(memoryview(blob))
                finally:
                    device_store.set_resolve_deadline(None)
            return self._deserialize_blob(memoryview(blob))
        val, found = self._try_local(oid)
        if found:
            return val
        # Remote fetch. Holders are shuffled so a hot object's readers fan
        # out across every node that already fetched a copy instead of all
        # hammering the producer — with add_location below this forms the
        # broadcast spread (reference push_manager's chunked broadcast).
        last_err = None
        holders = list(holders)
        if len(holders) > 1:
            import random

            random.shuffle(holders)
        for holder in holders:
            if tuple(holder) == tuple(self.server_addr):
                continue
            try:
                ok = self._fetch_from(tuple(holder), oid, deadline)
                if ok:
                    self.io.spawn(self.controller.push(
                        "add_location", oid=oid,
                        holder=self.agent_addr or self.server_addr))
                    mv = self.store.get(oid)
                    if mv is not None:
                        return self._deserialize_blob(mv)
            except Exception as e:  # holder gone; try next
                last_err = e
        # all holders failed -> try lineage reconstruction
        if self._maybe_reconstruct(oid):
            return self._get_one(ObjectRef(oid), deadline)
        raise exc.ObjectLostError(
            f"object {oid[:16]} unavailable (holders {holders}): {last_err}")

    def prefetch_object(self, oid: str, timeout: float = 120.0) -> None:
        """Localize an object's BYTES into this process's reach (inline
        cache or local shm) without deserializing — the warm-up half of
        _get_one for executor-side arg pre-localization (reference
        dependency_manager.h). Best-effort: failures are left for the real
        decode to surface."""
        if oid in self._inline_cache or self.store.contains(oid):
            return
        deadline = time.monotonic() + timeout
        res = self._resolutions.get(oid)
        if res is not None:
            if not res.wait(timeout):
                return
            holders, error, inline = res.holders, res.error, res.inline
        else:
            rep = self.io.run(self.controller.call(
                "wait_object", oid=oid, timeout=timeout))
            if rep["status"] != "ready":
                return
            holders = [tuple(h) for h in rep.get("holders", [])]
            error, inline = rep.get("error"), rep.get("inline")
        if error is not None or inline is not None or not holders:
            return  # inline/error payloads need no localization
        import random

        holders = list(holders)
        random.shuffle(holders)
        for holder in holders:
            if tuple(holder) == tuple(self.server_addr):
                return
            try:
                if self._fetch_from(tuple(holder), oid, deadline):
                    return
            except Exception:
                continue

    def _acquire_pull(self, nbytes: int):
        """Admission control (reference pull_manager.h:49): bound the bytes
        in flight across concurrent fetches. A single fetch is always
        admitted even when larger than the budget (no starvation)."""
        cap = CONFIG.pull_max_inflight_bytes
        with self._pull_cv:
            while self._pull_inflight > 0 and self._pull_inflight + nbytes > cap:
                self._pull_cv.wait(timeout=1.0)
            self._pull_inflight += nbytes

    def _release_pull(self, nbytes: int):
        with self._pull_cv:
            self._pull_inflight -= nbytes
            self._pull_cv.notify_all()

    def _fetch_from(self, holder: tuple, oid: str, deadline) -> bool:
        """Fetch an object into the local store in bounded chunks, with the
        NEXT chunk's request already in flight while the current chunk is
        copied into the stream segment — socket recv overlaps the memcpy
        (double buffering through LocalStore.begin_stream). Returns True
        once a local copy exists (including 'someone else fetched it
        first')."""
        chunk = CONFIG.object_chunk_bytes
        held = 2 * chunk  # double buffering holds up to two chunks in flight
        self._acquire_pull(held)
        try:
            rem = self._remaining(deadline)
            return self.io.run(
                self._a_fetch_from(holder, oid, chunk, rem),
                timeout=None if rem is None else rem + 5)
        except (asyncio.TimeoutError, _FuturesTimeout):
            raise exc.GetTimeoutError(f"fetch of {oid[:16]} timed out")
        finally:
            self._release_pull(held)

    async def _a_fetch_from(self, holder: tuple, oid: str, chunk: int,
                            timeout: float | None) -> bool:
        if timeout is not None:
            return await asyncio.wait_for(
                self._a_fetch_pipeline(holder, oid, chunk), timeout)
        return await self._a_fetch_pipeline(holder, oid, chunk)

    async def _a_fetch_pipeline(self, holder: tuple, oid: str,
                                chunk: int) -> bool:
        conn = await rpc.connect(*holder, timeout=5)
        stream = None
        nxt = None
        try:
            rep = await conn.call("fetch_object", oid=oid, offset=0,
                                  length=chunk)
            if not rep.get("found"):
                return False
            size = rep["size"]
            data = rep["data"]
            if size <= len(data):
                self.store.put(oid, [data])
                return True
            stream = self.store.begin_stream(oid, size)
            if stream is None:
                return True  # raced: a local copy already exists
            off = len(data)
            woff = 0
            while True:
                # Pipeline: request chunk k+1 BEFORE copying chunk k, and
                # do the copy in a worker thread so the event loop keeps
                # receiving the next chunk during the memcpy.
                nxt = (await conn.call_start("fetch_object", oid=oid,
                                             offset=off, length=chunk)
                       if off < size else None)
                await asyncio.to_thread(stream.write, woff, data)
                del data
                if nxt is None:
                    break
                rep = await nxt
                nxt = None
                if not rep.get("found"):
                    return False  # holder dropped it mid-stream
                data = rep["data"]
                woff = off
                off += len(data)
                del rep
            sealed = stream.seal()
            stream = None
            # seal() returning False means a concurrent fetch won the race
            # (a local copy exists) or the rename failed; either way the
            # store lookup below decides, so only claim success when the
            # object is actually there.
            return sealed or self.store.contains(oid)
        finally:
            if nxt is not None:
                # Cancellation/copy failure left the one-ahead request
                # un-awaited: consume its eventual error (call_start's
                # contract) so the loop never logs an unretrieved exception.
                nxt.add_done_callback(
                    lambda f: f.cancelled() or f.exception())
            if stream is not None:
                stream.abort()
            asyncio.ensure_future(conn.close())

    def _maybe_reconstruct(self, oid: str) -> bool:
        """Lineage reconstruction: resubmit the producing task (reference
        object_recovery_manager.cc:26 RecoverObject)."""
        if not CONFIG.lineage_reconstruction_enabled:
            return False
        spec = self._lineage.get(oid)
        if spec is None:
            return False
        logger.warning("reconstructing %s via task %s", oid[:12], spec.name)
        self._reset_resolution(oid)
        spec.attempt += 1
        self.io.run(self.controller.call("submit_task", spec=spec))
        return True

    def _reset_resolution(self, oid: str):
        res = self._resolutions.get(oid)
        if res is None:
            self._resolutions[oid] = _Resolution()
        else:
            res.reset()

    def _maybe_reconstruct_async(self, oid: str) -> bool:
        """Same as _maybe_reconstruct but safe to call ON the IO loop."""
        if not CONFIG.lineage_reconstruction_enabled:
            return False
        spec = self._lineage.get(oid)
        if spec is None:
            return False
        logger.warning("reconstructing %s via task %s (async)", oid[:12], spec.name)
        self._reset_resolution(oid)
        spec.attempt += 1
        asyncio.ensure_future(self.controller.call("submit_task", spec=spec))
        return True

    _NO_REFS_NO_BUFS = b"\x00" * 8  # [nrefs=0][nbufs=0] wire prefix

    def _deserialize_blob(self, mv):
        # Fast path for the dominant result shape (scalar/None, no embedded
        # refs, no oob buffers): one loads() straight off the header slice —
        # skips the SerializedObject parse + ref re-hydration machinery
        # (~2us/call at n:n harvest rates).
        if bytes(mv[:8]) == self._NO_REFS_NO_BUFS:
            (hlen,) = struct.unpack_from("<Q", mv, 8)
            return pickle.loads(mv[16:16 + hlen])
        return self._deser_with_refs(SerializedObject.from_buffer(mv))

    def _deser_with_refs(self, sobj: SerializedObject):
        # contained_refs are ObjectRef instances (fresh from serialize()) or
        # oid hex strings (parsed from a flattened blob) — re-hydrate either.
        refs = [
            r if isinstance(r, ObjectRef)
            else ObjectRef(r, owned=False, worker=self, borrow=True)
            for r in sobj.contained_refs
        ]
        return deserialize(sobj, resolve_ref=lambda idx: refs[idx])

    def _decode_error(self, error_parts) -> Exception:
        blob = loads_oob(bytes(error_parts[0]), [memoryview(p) for p in error_parts[1:]])
        etype = blob.get("type")
        if etype == "TaskError":
            cause = None
            if blob.get("cause") is not None:
                try:
                    cause = loads_oob(bytes(blob["cause"]), [])
                except Exception:
                    cause = None
            err = exc.TaskError(blob.get("function_name", "?"), blob.get("traceback", ""), cause)
            if cause is not None and isinstance(cause, Exception):
                err.__cause__ = cause
            return err
        if etype == "WorkerCrashedError":
            return exc.WorkerCrashedError(blob.get("message", ""))
        if etype == "OutOfMemoryError":
            return exc.OutOfMemoryError(blob.get("message", ""))
        if etype == "ActorDiedError":
            return exc.ActorDiedError(blob.get("message", ""))
        if etype == "TaskCancelledError":
            return exc.TaskCancelledError(blob.get("message", "task cancelled"))
        if etype == "TaskTimeoutError":
            return exc.TaskTimeoutError(blob.get("message", "task exceeded its timeout_s"))
        if etype == "ObjectLostError":
            return exc.ObjectLostError(blob.get("message", "object lost"))
        return exc.RayTpuError(str(blob))

    # ---------------------------------------------------------------- wait
    def wait(self, refs: list[ObjectRef], num_returns: int = 1, timeout: float | None = None):
        """Event-driven wait (reference raylet/wait_manager.h is similarly
        notification-based): owned refs hook resolution watchers and sleep on
        one Event — no polling, no controller traffic. Only refs owned by
        ANOTHER process (no local resolution slot) fall back to polling the
        controller's bulk readiness probe."""
        if num_returns > len(refs):
            raise ValueError("num_returns > len(refs)")
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list[ObjectRef] = []
        owned_pending: list[ObjectRef] = []
        borrowed_pending: list[ObjectRef] = []
        for r in refs:
            oid = r.hex()
            if self._is_ready_local(oid):
                ready.append(r)
            elif oid in self._resolutions:
                owned_pending.append(r)
            else:
                borrowed_pending.append(r)
        if len(ready) >= num_returns or not (owned_pending or borrowed_pending):
            return ready, owned_pending + borrowed_pending
        ev = threading.Event()
        hits: list[ObjectRef] = []
        hits_lock = threading.Lock()
        live = [True]  # watchers outlive this call; dead-man switch

        def _mk_cb(r):
            def cb():
                if live[0]:
                    with hits_lock:
                        hits.append(r)
                    ev.set()
            return cb

        registered: list[tuple] = []  # (res, cb) to deregister on exit
        try:
            for r in owned_pending:
                res = self._resolutions.get(r.hex())
                cb = _mk_cb(r)
                if res is None or not res.add_watcher(cb):
                    cb()  # resolved between classification and registration
                else:
                    registered.append((res, cb))
            owned_waiting = set(owned_pending)
            while True:
                with hits_lock:
                    newly, hits[:] = list(hits), []
                for r in newly:
                    if r in owned_waiting:
                        owned_waiting.discard(r)
                        ready.append(r)
                if len(ready) >= num_returns or not (owned_waiting or borrowed_pending):
                    break
                if borrowed_pending:
                    oids = [r.hex() for r in borrowed_pending]
                    rep = self.io.run(self.controller.call("check_objects", oids=oids))
                    newly_b = [r for r, ok in zip(borrowed_pending, rep["ready"]) if ok]
                    ready.extend(newly_b)
                    borrowed_pending = [
                        r for r, ok in zip(borrowed_pending, rep["ready"]) if not ok]
                    if len(ready) >= num_returns or not (owned_waiting or borrowed_pending):
                        break
                rem = None if deadline is None else deadline - time.monotonic()
                if rem is not None and rem <= 0:
                    break
                # With borrowed refs in play we must re-poll the controller;
                # otherwise sleep until a watcher fires (or timeout).
                if borrowed_pending:
                    rem = 0.005 if rem is None else min(rem, 0.005)
                ev.wait(rem)
                ev.clear()
        finally:
            live[0] = False
            # Deregister un-fired watchers: a caller polling wait() in a
            # loop against a slow task must not grow the resolution's
            # watcher list (and pin refs) on every call.
            for res, cb in registered:
                res.remove_watcher(cb)
        return ready, [r for r in owned_pending if r in owned_waiting] + borrowed_pending

    def _is_ready_local(self, oid: str) -> bool:
        if oid in self._inline_cache or self.store.contains(oid):
            return True
        res = self._resolutions.get(oid)
        return res is not None and res.done

    # ------------------------------------------------- streaming generators
    def _gen_new(self, spec: TaskSpec) -> "ObjectRefGenerator":
        """Register owner-side stream state for a streaming spec (whose
        completion resolution must already exist) and return the public
        generator object."""
        comp_oid = spec.return_object_ids()[0]
        thresh = CONFIG.generator_backpressure_items
        # stride 0 = backpressure disabled: send no acks at all (the
        # executor ignores them anyway).
        stride = max(1, thresh // 4) if thresh > 0 else 0
        gs = _GenState(spec.task_id, stride)
        self._generators[spec.task_id] = gs
        res = self._resolutions[comp_oid]

        def _fin():
            total, err = None, res.error
            if err is None and res.inline is not None:
                try:
                    blob = (res.inline[0] if len(res.inline) == 1
                            else b"".join(bytes(p) for p in res.inline))
                    total = int(self._deserialize_blob(memoryview(blob)))
                except Exception:
                    total = None
            gs.finish(total, err)

        if not res.add_watcher(_fin):
            _fin()
        return ObjectRefGenerator(
            self, spec.task_id, ObjectRef(comp_oid, owned=True, worker=self))

    def _on_gen_items(self, conn, items):
        """Incremental item reports from the executing worker (runs on the
        IO loop; reference ReportGeneratorItemReturns handler). A retry
        re-reports indices the owner already has — re-resolve (idempotent)
        but never re-queue."""
        closed: set[str] = set()
        for tid, idx, result in items:
            oid, inline, size, holder = result
            gs = self._generators.get(tid)
            if gs is None:
                # Generator destroyed before the stream drained: drop the
                # straggler and tell the executor to stop producing (its
                # backpressure wait would otherwise never end — actor-task
                # streams have no lease/controller cancel path).
                res = self._resolutions.setdefault(oid, _Resolution())
                res.resolve(inline, [tuple(holder)] if holder else [], None)
                self._free([oid])
                closed.add(tid)
                continue
            with gs.cond:
                gs.conn = conn
                fresh = idx >= gs.produced
                if fresh:
                    gs.produced = idx + 1
            if fresh:
                res = self._resolutions.setdefault(oid, _Resolution())
                res.resolve(inline, [tuple(holder)] if holder else [], None)
                with gs.cond:
                    gs.queue.append(oid)
                    gs.cond.notify_all()
                if self._generators.get(tid) is not gs:
                    # _gen_destroy ran between our registry fetch and the
                    # append: its queue-snapshot free missed this item, so
                    # drain-and-free here (double free is idempotent).
                    with gs.cond:
                        orphaned = list(gs.queue)
                        gs.queue.clear()
                    if orphaned:
                        self._free(orphaned)
                    closed.add(tid)
            else:
                # Retry re-report of an index we already have. Re-resolve
                # ONLY if the resolution still exists (a live ref or queued
                # item) — recreating one for a consumed-and-freed item would
                # leak it forever.
                res = self._resolutions.get(oid)
                if res is not None:
                    res.resolve(inline, [tuple(holder)] if holder else [], None)
        for tid in closed:
            try:
                conn.push_threadsafe("gen_close", task_id=tid)
            except Exception:
                pass

    def _gen_conn_lost(self, conn):
        """Called by the lease manager / actor pipe when a connection that
        carried stream items closes: truncate any stream whose trailing
        items were provably lost (see _GenState.conn_lost). Streams whose
        spec is still tracked (retry/fail) are handled by those paths."""
        # conn is None: a completed stream that never received items on ANY
        # connection (e.g. the completion landed but the executor died
        # before flushing items) must still be truncated — conn_lost()
        # itself requires done && produced < total, so fresh streams on
        # other connections are untouched.
        gens = [gs for gs in self._generators.values()
                if gs.conn is conn or (gs.conn is None and gs.done)]
        if not gens:
            return
        h, bufs = dumps_oob({
            "type": "WorkerCrashedError",
            "message": "stream truncated: executor connection lost with "
                       "trailing items undelivered"})
        for gs in gens:
            gs.conn_lost([h, *bufs])

    def _gen_destroy(self, task_id: str):
        """Generator object GC'd: free unconsumed items, cancel a stream
        still in flight (reference: deleting the generator cancels the task
        and GCs unconsumed returns)."""
        gs = self._generators.pop(task_id, None)
        if gs is None or self._shutdown:
            return
        with gs.cond:
            pending = list(gs.queue)
            gs.queue.clear()
            done = gs.done
            conn = gs.conn
        if pending:
            try:
                self._free(pending)
            except Exception:
                pass
        if not done and conn is not None:
            # Direct stop signal to the executor: actor-task streams have no
            # cancel path through the lease manager or controller, and the
            # producer may be parked in a backpressure wait.
            try:
                conn.push_threadsafe("gen_close", task_id=task_id)
            except Exception:
                pass
        if not done:
            # cancel_task blocks on the IO loop; __del__ may run on any
            # thread (including the loop itself), so hop to a helper thread.
            def _bg():
                try:
                    self.cancel_task(task_id, False)
                except Exception:
                    pass

            threading.Thread(target=_bg, daemon=True,
                             name="rt-gen-cancel").start()

    # --------------------------------------------------------- submit task
    def _register_function(self, fn) -> str:
        # Hot path: serializing the function (closure walk) costs far more
        # than the submit itself — cache by object identity so a @remote
        # function is pickled once per process (reference function_manager
        # exports once per function id).
        try:
            fid = self._fn_id_cache.get(fn)
        except TypeError:  # unhashable/unweakrefable callables: no cache
            fid = None
        if fid is not None:
            return fid
        blob = serialize(fn, ref_class=ObjectRef)
        if blob.contained_refs:
            raise ValueError("remote function may not close over ObjectRefs; pass them as args")
        data = blob.to_bytes()
        import hashlib

        fid = hashlib.sha1(data).hexdigest()
        if fid not in self._registered_fns:
            self.io.run(self.controller.call("kv_put", ns="fn", key=fid, value=data, overwrite=False))
            self._registered_fns.add(fid)
        try:
            self._fn_id_cache[fn] = fid
        except TypeError:
            pass
        return fid

    def load_function(self, fid: str):
        fn = self._fn_cache.get(fid)
        if fn is None:
            rep = self.io.run(self.controller.call("kv_get", ns="fn", key=fid))
            if rep["value"] is None:
                raise exc.RayTpuError(f"function {fid} not found in KV")
            sobj = SerializedObject.from_buffer(memoryview(rep["value"]))
            fn = self._deser_with_refs(sobj)
            self._fn_cache[fid] = fn
        return fn

    def _encode_args(self, args, kwargs):
        """Returns (enc_args, enc_kwargs, escaping_oids, dref_oids).
        escaping_oids are the refs shipped inside this payload — the
        submitter must PIN the owned ones until the task completes
        (reference: task arguments hold references, reference_count.h
        AddLocalReference for args), or rebinding the Python variable frees
        the arg before the worker can read it. dref_oids are device-plane
        arg promotions, holding one refcount from _encode_one that the
        submit path must tie to the task's return ref (_register_arg_pins)
        or the pinned device memory outlives every reference to it."""
        escapes: list[str] = []
        drefs: list[str] = []
        enc_args = [self._encode_one(a, escapes, drefs) for a in args]
        enc_kwargs = {k: self._encode_one(v, escapes, drefs)
                      for k, v in kwargs.items()}
        return enc_args, enc_kwargs, escapes, drefs

    def _encode_one(self, value, escapes: list | None = None,
                    drefs: list | None = None):
        if isinstance(value, ObjectRef):
            oid = value.hex()
            self._advertise_escaping([oid])
            if escapes is not None:
                escapes.append(oid)
            return ("ref", oid)
        if device_store.eligible(value):
            # Large device-array argument: pin instead of copying through
            # the host store; the placeholder blob rides INSIDE the spec
            # (task_spec.DEVICE_REF) so the executor resolves it from the
            # location hint with no controller round trip. The incref is
            # the submit-time hold; _register_arg_pins drops it when the
            # task's return ref dies.
            oid, blob = self._put_device(value)
            self._incref(oid)
            if drefs is not None:
                drefs.append(oid)
            return (DEVICE_REF, oid, blob)
        sobj = serialize(value, ref_class=ObjectRef)
        if sobj.contained_refs:
            oids = [r.hex() if isinstance(r, ObjectRef) else r
                    for r in sobj.contained_refs]
            self._advertise_escaping(oids)
            if escapes is not None:
                escapes.extend(oids)
        if sobj.total_bytes() <= CONFIG.max_inline_object_bytes:
            return ("v", sobj.to_bytes())
        # Large argument: promote to an owned object (reference puts >100KB
        # args in plasma — remote_function.py _remote).
        oid = ObjectID.from_put().hex()
        self._store_blob(oid, sobj, register=True)
        self._incref(oid)  # pinned for the duration of the session put
        return ("ref", oid)

    def _pin_args_until_done(self, escapes: list[str], refs: list):
        """incref owned arg refs now; decref when the task's first return
        resolves (value, error, or cancellation all resolve)."""
        if not escapes or not refs:
            return
        pinned = [o for o in escapes if o in self._refcounts]
        if not pinned:
            return
        for o in pinned:
            self._incref(o)
        res = self._resolutions.get(refs[0].hex())
        if res is None:
            for o in pinned:
                self._decref(o)
            return
        def _unpin(_pinned=tuple(pinned)):
            for o in _pinned:
                self._decref(o)

        if not res.add_watcher(_unpin):
            _unpin()  # already resolved

    def _register_arg_pins(self, drefs: list[str], refs: list):
        """Tie device-arg pins to the task's return refs: one hold per
        return ref (the _encode_one incref covers the first; extras are
        taken here), dropped as each ref is freed — so the pins outlive
        any window where ANY result could still be lineage-reconstructed
        (reconstruction re-runs the spec, which re-resolves the dref blobs
        from this table), without holding device memory for the whole
        session. No refs (fire-and-forget num_returns=0) keeps the session
        hold — nothing observable ever says the task is done."""
        if not drefs or not refs:
            return
        for i, r in enumerate(refs):
            if i > 0:
                for o in drefs:
                    self._incref(o)
            key = r.hex()
            prev = self._arg_pins.get(key)
            self._arg_pins[key] = ((tuple(prev) + tuple(drefs)) if prev
                                   else tuple(drefs))

    def _advertise_escaping(self, oids: list[str]):
        """Owner-side escape analysis at the serialization boundary: a ref
        can only be BORROWED after its owner ships it inside a payload, so
        inline results (which are no longer eagerly advertised on the
        direct-call paths) are registered with the controller exactly when
        they first escape. Shm results and puts are advertised at creation
        (they name a fetchable holder); borrowed refs are skipped (their
        owner advertised them before they reached us)."""
        for oid in oids:
            if oid in self._escaped:
                continue
            res = self._resolutions.get(oid)
            if res is None:
                continue  # not ours
            self._escaped.add(oid)
            cb = (lambda o=oid, r=res: self._push_escape_advertise(o, r))
            if not res.add_watcher(cb):
                cb()  # already resolved: advertise now

    def _push_escape_advertise(self, oid: str, res: "_Resolution"):
        if res.inline is None and res.error is None:
            return  # shm result: the executing worker advertised the holder
        size = sum(len(p) for p in res.inline) if res.inline else 0
        try:
            self.controller.push_threadsafe(
                "register_put", oid=oid, size=size, inline=res.inline,
                holder=None, owner=self.worker_id, error=res.error)
        except Exception:
            pass

    def decode_args(self, enc_args, enc_kwargs):
        if not enc_args and not enc_kwargs:
            return (), {}
        args = [self._decode_one(e) for e in enc_args]
        kwargs = {k: self._decode_one(e) for k, e in enc_kwargs.items()}
        return args, kwargs

    def _decode_one(self, e):
        kind = e[0]
        if kind == "ref":
            return self._get_one(ObjectRef(e[1]), deadline=None)
        if kind == DEVICE_REF:
            # Device-plane argument: the placeholder carries its own
            # location hint — deserializing resolves through the tier
            # ladder directly (no wait_object round trip).
            return self._deserialize_blob(memoryview(e[2]))
        return self._deserialize_blob(memoryview(e[1]))

    def submit_task(self, fn, args, kwargs, *, name=None, num_returns=1, resources: ResourceSet,
                    strategy: SchedulingStrategy | None = None, max_retries: int | None = None,
                    retry_exceptions=False, runtime_env=None,
                    timeout_s: float | None = None) -> list[ObjectRef]:
        streaming = num_returns == STREAMING
        if streaming and any(k.startswith("TPU") for k in resources.raw()):
            raise ValueError(
                "num_returns='streaming' tasks ride the direct lease path; "
                "TPU tasks use controller dispatch. Host a streaming method "
                "on a TPU actor instead.")
        if runtime_env:
            from ray_tpu._private import runtime_env as _rtenv

            runtime_env = _rtenv.package(self, runtime_env)
        fid = self._register_function(fn)
        enc_args, enc_kwargs, escapes, drefs = (
            self._encode_args(args, kwargs)
            if (args or kwargs) else ([], {}, [], []))
        task_id = TaskID.from_random().hex()
        spec = TaskSpec(
            task_id=task_id,
            kind=NORMAL,
            name=name or getattr(fn, "__name__", "task"),
            function_id=fid,
            args=enc_args,
            kwargs=enc_kwargs,
            num_returns=num_returns,
            resources=resources.raw(),
            strategy=strategy or SchedulingStrategy(),
            max_retries=CONFIG.default_max_task_retries if max_retries is None else max_retries,
            retry_exceptions=retry_exceptions,
            runtime_env=runtime_env or {},
            owner_id=self.worker_id,
            owner_addr=self.server_addr,
            timeout_s=timeout_s,
        )
        if _tracing.enabled():
            # Submit span + wire context: inside a traced task this chains
            # to the executing span; at top level it roots a new trace
            # (head-based RT_TRACE_SAMPLE decision).
            spec.trace = _tracing.on_submit(spec.name, task_id)
        refs = []
        for oid in spec.return_object_ids():
            self._resolutions[oid] = _Resolution()
            # Streaming tasks retry via lease requeue, not lineage: the
            # controller-dispatch reconstruction path has no item transport.
            if spec.max_retries != 0 and not streaming:
                self._lineage[oid] = spec
            refs.append(ObjectRef(oid, owned=True, worker=self))
        # drefs ride the until-done pin too: a fire-and-forget caller drops
        # the result ref instantly, and without the completion hold the
        # per-ref release would free the pinned arg before the executor
        # decodes it (the host path gets this from the same call).
        self._pin_args_until_done(escapes + drefs, refs)
        self._register_arg_pins(drefs, refs)
        if streaming:
            # Streaming always rides the direct path (the controller
            # transport has no item stream), RT_DIRECT_DISPATCH or not.
            gen = self._gen_new(spec)
            self.lease_mgr.submit(spec)
            return gen
        # Direct path: lease workers by scheduling class and stream specs to
        # them (reference NormalTaskSubmitter lease pools). TPU tasks keep
        # the controller-dispatch path — they need a dedicated worker whose
        # chip lease dies with the process. RT_DIRECT_DISPATCH=0 routes
        # everything through the controller (the classic path; also the
        # perf-gate comparison workload).
        if (CONFIG.direct_dispatch
                and not any(k.startswith("TPU") for k in spec.resources)):
            self.lease_mgr.submit(spec)
            return refs
        self.submit_specs_via_controller([spec])
        return refs

    def submit_specs_via_controller(self, specs: list):
        """Queue already-built specs on the classic controller dispatch
        path (TPU tasks, RT_DIRECT_DISPATCH=0, and direct-dispatch
        failover). Thread-safe; bursts coalesce into one `submit_tasks`
        frame via the flusher."""
        _record_dispatch("controller", len(specs))
        # Coalesced submit: bursts of .remote() calls ride one RPC frame
        # (reference batches task submission through the Cython layer; here
        # the flusher drains whatever accumulated while the previous frame
        # was in flight).
        with self._submit_lock:
            self._submit_buf.extend(specs)
            need_flush = not self._submit_flushing
            self._submit_flushing = True
        if need_flush:
            self.io.spawn(self._a_flush_submits())

    def cancel_task(self, task_id: str, force: bool):
        """Cancel a task wherever it lives: the owner's lease pipelines (the
        direct path) or the controller queue (TPU/legacy/reconstruction)."""
        if self.lease_mgr.cancel(task_id, force):
            return {"status": "cancelled_direct"}
        return self.io.run(self.controller.call(
            "cancel_task", task_id=task_id, force=force))

    async def _a_flush_submits(self):
        while True:
            with self._submit_lock:
                batch = list(self._submit_buf)
                self._submit_buf.clear()
                if not batch:
                    self._submit_flushing = False
                    return
            try:
                # Acked call, not a push: with coalesced writes a push
                # "succeeds" once buffered, so a connection dying before
                # the flush would silently strand the batch's refs forever.
                # One round-trip per BATCH keeps the ack off the per-task
                # cost.
                await self.controller.call("submit_tasks", specs=batch)
            except Exception as e:
                # The push failed after the specs left the buffer: fail the
                # batch's refs so callers see an error instead of a hang —
                # including anything that accumulated while the push was in
                # flight (no new flusher was spawned for those specs).
                with self._submit_lock:
                    batch.extend(self._submit_buf)
                    self._submit_buf.clear()
                    self._submit_flushing = False
                h, bufs = dumps_oob({"type": "WorkerCrashedError",
                                     "message": f"task submission failed: {e}"})
                for spec in batch:
                    for oid in spec.return_object_ids():
                        res = self._resolutions.setdefault(oid, _Resolution())
                        res.resolve(None, [], [h, *bufs])
                return

    # -------------------------------------------------------------- actors
    def create_actor(self, cls, args, kwargs, *, name=None, namespace="default",
                     get_if_exists=False, resources: ResourceSet,
                     strategy: SchedulingStrategy | None = None, max_restarts=0,
                     max_task_retries=0, max_concurrency=1, runtime_env=None,
                     actor_display_name=None, lifetime=None,
                     concurrency_groups=None) -> str:
        from ray_tpu._private.ids import ActorID

        if runtime_env:
            from ray_tpu._private import runtime_env as _rtenv

            runtime_env = _rtenv.package(self, runtime_env)
        fid = self._register_function(cls)
        enc_args, enc_kwargs, escapes, _drefs = self._encode_args(args, kwargs)
        # Actor init args must survive RESTARTS (the controller re-runs
        # __init__ from the same spec), so owned arg refs stay pinned for
        # the session (reference: the GCS holds actor creation specs) —
        # device-arg pins (_drefs) keep their session hold for the same
        # reason: a restart re-resolves them from the submitter's table.
        for o in escapes:
            if o in self._refcounts:
                self._incref(o)
        actor_id = ActorID.from_random().hex()
        spec = TaskSpec(
            task_id=TaskID.from_random().hex(),
            kind=ACTOR_CREATE,
            name=actor_display_name or getattr(cls, "__name__", "actor"),
            function_id=fid,
            args=enc_args,
            kwargs=enc_kwargs,
            num_returns=0,
            resources=resources.raw(),
            strategy=strategy or SchedulingStrategy(),
            runtime_env=runtime_env or {},
            owner_id=self.worker_id,
            owner_addr=self.server_addr,
            actor_id=actor_id,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            actor_name=name,
            namespace=namespace,
            get_if_exists=get_if_exists,
            lifetime=lifetime,
            concurrency_groups=dict(concurrency_groups) if concurrency_groups else None,
        )
        if _tracing.enabled():
            spec.trace = _tracing.on_submit(spec.name, spec.task_id)
        rep = self.io.run(self.controller.call("create_actor", spec=spec))
        return rep["actor_id"]

    async def _a_resolve_actor(self, actor_id: str, wait=True, timeout=60.0) -> dict:
        info = self._actor_info.get(actor_id)
        if info is not None and info.get("state") == "ALIVE":
            return info
        rep = await self.controller.call(
            "get_actor_info", actor_id=actor_id, wait=wait, timeout=timeout)
        if rep["status"] != "ok":
            raise exc.ActorDiedError(f"actor {actor_id[:12]} not found")
        if rep["state"] == "DEAD":
            if rep.get("death_cause"):
                raise self._decode_error(rep["death_cause"])
            raise exc.ActorDiedError(f"actor {actor_id[:12]} is dead")
        self._actor_info[actor_id] = rep
        return rep


    def submit_actor_task(self, actor_id: str, method_name: str, args, kwargs, *,
                          num_returns=1, name=None, max_task_retries=0) -> list[ObjectRef]:
        enc_args, enc_kwargs, escapes, drefs = (
            self._encode_args(args, kwargs)
            if (args or kwargs) else ([], {}, [], []))
        task_id = TaskID.from_random().hex()
        spec = TaskSpec.for_actor_call(
            task_id, method_name, enc_args, enc_kwargs, num_returns,
            name or method_name, self.worker_id, self.server_addr, actor_id)
        if _tracing.enabled():
            spec.trace = _tracing.on_submit(spec.name, task_id)
        refs = []
        for oid in spec.return_object_ids():
            self._resolutions[oid] = _Resolution()
            refs.append(ObjectRef(oid, owned=True, worker=self))
        if escapes or drefs:
            # drefs included: the completion hold keeps a fire-and-forget
            # call's pinned args alive until the executor is done with them
            # (see submit_task).
            self._pin_args_until_done(escapes + drefs, refs)
        self._register_arg_pins(drefs, refs)
        gen = self._gen_new(spec) if num_returns == STREAMING else None
        pipe = self._actor_pipes.get(actor_id)
        if pipe is None:
            with self._submit_lock:
                pipe = self._actor_pipes.get(actor_id)
                if pipe is None:
                    pipe = self._actor_pipes[actor_id] = _ActorPipe(self, actor_id)
        pipe.submit(spec, max(0, max_task_retries))
        return gen if gen is not None else refs

    def _fail_actor_call(self, spec: TaskSpec, e: Exception):
        blob = {"type": "ActorDiedError", "message": str(e)}
        if isinstance(e, exc.TaskError):
            blob = {"type": "TaskError", "function_name": spec.name,
                    "traceback": str(e), "cause": None}
        h, bufs = dumps_oob(blob)
        for oid in spec.return_object_ids():
            res = self._resolutions.setdefault(oid, _Resolution())
            res.resolve(None, [], [h, *bufs])

    def _apply_actor_reply(self, spec: TaskSpec, rep: tuple):
        # rep: (task_id, attempt, results, error, retryable, exec_failure)
        _tid, _attempt, results, error, _retryable, exec_failure = rep  # rtcheck: wire=tasks_done.item
        if spec.trace is not None:
            _tracing.record_instant(
                spec.trace, "result", "result",
                {"task": spec.task_id, "ok": error is None})
        if exec_failure and not results:
            # The actor's executor layer failed before results were packaged:
            # fail the refs rather than leaving the caller blocked forever.
            self._fail_actor_call(spec, exc.ActorUnavailableError(
                f"actor executor failure: {exec_failure}"))
            return
        for oid, inline, size, holder in results or ():
            res = self._resolutions.setdefault(oid, _Resolution())
            res.resolve(inline, [tuple(holder)] if holder else [], error)

    def _schedule_pipe_pump(self, pipe: "_ActorPipe"):
        """Coalesced cross-thread pump scheduling for actor pipes (see
        _pump_pipes). Called from any thread with pipe.pumping already
        claimed by the caller."""
        with self._submit_lock:
            self._pump_pipes.append(pipe)
            if self._pipe_pump_scheduled:
                return
            self._pipe_pump_scheduled = True
        self.io.spawn(self._a_pump_pipes())

    async def _a_pump_pipes(self):
        while True:
            with self._submit_lock:
                pipes, self._pump_pipes = self._pump_pipes, []
                if not pipes:
                    self._pipe_pump_scheduled = False
                    return
            for pipe in pipes:
                # Fan out ON the loop: one pipe's slow connect must not
                # stall its siblings' flushes.
                asyncio.ensure_future(pipe._a_pump())

    def kill_actor(self, actor_id: str, no_restart=True):
        self.io.run(self.controller.call("kill_actor", actor_id=actor_id, no_restart=no_restart))
        self._actor_info.pop(actor_id, None)

    # ------------------------------------------------------------- cluster
    def cluster_resources(self) -> dict:
        return self.io.run(self.controller.call("cluster_resources"))

    def state_snapshot(self) -> dict:
        return self.io.run(self.controller.call("state_snapshot"))

    def kv(self, op: str, **kw):
        return self.io.run(self.controller.call(f"kv_{op}", **kw))


class _ActorPipe:
    """Ordered, pipelined transport to one actor.

    Bursts of calls ride coalesced `actor_tasks` frames; replies come back
    as batched `tasks_done` pushes keyed by task_id (so out-of-order
    completion from async/threaded actors resolves correctly). On connection
    loss, in-flight calls with retries left are resubmitted IN ORDER across
    the actor restart; the rest fail with ActorDiedError (reference
    ActorTaskSubmitter restart semantics)."""

    __slots__ = ("w", "actor_id", "lock", "queue", "inflight", "seq", "conn",
                 "pumping")

    def __init__(self, worker: "Worker", actor_id: str):
        self.w = worker
        self.actor_id = actor_id
        self.lock = threading.Lock()
        self.queue: deque = deque()
        self.inflight: dict[str, tuple] = {}  # task_id -> (spec, retries, seq)
        self.seq = 0
        self.conn = None
        self.pumping = False

    def submit(self, spec: TaskSpec, retries: int):
        with self.lock:
            self.seq += 1
            self.queue.append((spec, retries, self.seq))
            need = not self.pumping
            self.pumping = True
        if need:
            self.w._schedule_pipe_pump(self)

    async def _a_pump(self):
        while True:
            if self.conn is None or self.conn.closed:
                if not await self._a_connect():
                    return  # everything failed; pumping reset by _a_connect
            with self.lock:
                batch = list(self.queue)
                self.queue.clear()
                if not batch:
                    self.pumping = False
                    return
            for spec, retries, seq in batch:
                self.inflight[spec.task_id] = (spec, retries, seq)
            try:
                # Compact wire form: frame-constant owner/actor fields ride
                # once, per-call fields as tuples (~3x cheaper than full
                # 24-field spec pickles at n:n call rates).
                await self.conn.push(
                    "actor_calls",
                    common=(self.w.worker_id, self.w.server_addr, self.actor_id),
                    calls=[b[0].actor_call_tuple() for b in batch])
            except Exception:
                pass  # close handler redistributes inflight; loop reconnects

    async def _a_connect(self) -> bool:
        attempts = 0
        while True:
            try:
                info = await self.w._a_resolve_actor(self.actor_id)
                if info.get("address") is None:
                    # Still PENDING (creation queued/scheduling — on a
                    # loaded cluster a big actor wave can take minutes):
                    # calls QUEUE until the actor lands (reference actor
                    # task submitter buffers until the actor is ready).
                    # A dead actor raises from _a_resolve_actor instead.
                    self.w._actor_info.pop(self.actor_id, None)
                    await asyncio.sleep(0.5)
                    continue
                conn = await rpc.connect(
                    *info["address"], on_push=self._on_push,
                    on_close=self._on_close, timeout=10,
                    label="actor-pipe")
                # A new worker may have reused a dead worker's port while the
                # controller still reports the old instance ALIVE: verify
                # identity before trusting the link.
                expect = info.get("worker_id")
                if expect is not None:
                    rep = await conn.call("whoami", _timeout=10)
                    if rep.get("worker_id") != expect:
                        await conn.close()
                        raise ConnectionError("stale actor address (port reused)")
                self.conn = conn
                return True
            except (exc.ActorError, exc.TaskError) as e:
                self._fail_all(e)
                return False
            except Exception as e:
                # Stale address / refused connection: the actor may be
                # mid-restart and not re-registered yet — re-resolve.
                self.w._actor_info.pop(self.actor_id, None)
                attempts += 1
                if attempts > 20:
                    self._fail_all(e, permanent=False)
                    return False
                await asyncio.sleep(0.1)

    def _fail_all(self, e: Exception, permanent: bool = True):
        with self.lock:
            q = list(self.queue)
            self.queue.clear()
            self.pumping = False
        inf = sorted(self.inflight.values(), key=lambda t: t[2])
        self.inflight.clear()
        for spec, _, _ in inf:
            self.w._fail_actor_call(spec, e)
        for spec, _, _ in q:
            self.w._fail_actor_call(spec, e)
        if permanent:
            # Keep the pipe reusable: a later submit re-resolves the actor
            # (named get_if_exists / restarted handles), failing fast again
            # if it is still dead.
            self.w._actor_info.pop(self.actor_id, None)

    async def _on_push(self, conn, method, a):
        if method == "gen_items":
            self.w._on_gen_items(conn, a["items"])
            return
        if method != "tasks_done":
            return
        for item in a["done"]:
            ent = self.inflight.pop(item[0], None)  # rtcheck: wire=tasks_done.item
            if ent is None:
                continue
            self.w._apply_actor_reply(ent[0], item)

    def _on_close(self, conn):
        if self.conn is not conn:
            return
        self.conn = None
        if self.w._shutdown:
            return
        self.w._gen_conn_lost(conn)
        self.w._actor_info.pop(self.actor_id, None)
        # Redistribute in-flight calls: retryable ones go back to the FRONT
        # of the queue in sequence order; the rest fail now.
        inf = sorted(self.inflight.values(), key=lambda t: t[2])
        self.inflight.clear()
        with self.lock:
            for spec, retries, seq in reversed(inf):
                if retries > 0:
                    self.queue.appendleft((spec, retries - 1, seq))
            need = bool(self.queue) and not self.pumping
            if need:
                self.pumping = True
        for spec, retries, _ in inf:
            if retries <= 0:
                self.w._fail_actor_call(spec, exc.ActorDiedError(
                    f"actor {self.actor_id[:12]} died mid-call"))
        if need:
            self.w.io.spawn(self._a_pump())
