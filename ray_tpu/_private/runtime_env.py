"""Runtime environments: working_dir / py_modules packaging + activation.

Parity target: reference python/ray/_private/runtime_env/ (working_dir.py,
py_modules.py, packaging.py:  zip the directory, content-address it as
gcs://_ray_pkg_<sha>.zip in the GCS KV, download+extract on the worker
node, chdir / sys.path-insert). env_vars are handled separately by the
worker pool (baked for dedicated workers, apply+restore per task for
pooled ones). pip/conda/container isolation is intentionally out of scope
(no package installs in the target environment); specifying them raises.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import threading
import zipfile

_MAX_PKG_BYTES = 200 * 1024 * 1024
_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}

_pack_cache: dict = {}  # abspath -> (stamp, sha)
_pack_lock = threading.Lock()

_UNSUPPORTED = ("pip", "conda", "container", "uv")


def validate(runtime_env: dict | None) -> None:
    for k in _UNSUPPORTED:
        if runtime_env and runtime_env.get(k):
            raise ValueError(
                f"runtime_env[{k!r}] is not supported in this environment "
                f"(no network package installs); bake dependencies into the "
                f"image or use py_modules/working_dir")


def _zip_dir(root: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
            for fn in sorted(filenames):
                full = os.path.join(dirpath, fn)
                rel = os.path.relpath(full, root)
                try:
                    zf.write(full, rel)
                except OSError:
                    continue  # vanished mid-walk
            if buf.tell() > _MAX_PKG_BYTES:
                raise ValueError(
                    f"runtime_env package {root!r} exceeds "
                    f"{_MAX_PKG_BYTES >> 20} MiB")
    return buf.getvalue()


def _dir_stamp(root: str) -> tuple:
    """Cheap change detector so repeat submissions don't re-zip."""
    latest = 0.0
    count = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
        for fn in filenames:
            try:
                latest = max(latest, os.stat(os.path.join(dirpath, fn)).st_mtime)
            except OSError:
                pass
            count += 1
    return (latest, count)


def package(worker, runtime_env: dict | None) -> dict | None:
    """Driver side: replace local working_dir / py_modules paths with
    content-addressed package ids uploaded to the controller KV."""
    validate(runtime_env)
    if not runtime_env:
        return runtime_env
    out = dict(runtime_env)

    def _upload(path: str) -> str:
        path = os.path.abspath(path)
        if not os.path.isdir(path):
            raise ValueError(f"runtime_env path {path!r} is not a directory")
        stamp = _dir_stamp(path)
        with _pack_lock:
            cached = _pack_cache.get(path)
            if cached is not None and cached[0] == stamp:
                return cached[1]
        blob = _zip_dir(path)
        sha = hashlib.sha256(blob).hexdigest()[:32]
        worker.kv("put", ns="pkg", key=sha, value=blob, overwrite=False)
        with _pack_lock:
            _pack_cache[path] = (stamp, sha)
        return sha

    wd = out.get("working_dir")
    if wd:
        out["working_dir_pkg"] = _upload(wd)
        del out["working_dir"]
    mods = out.get("py_modules")
    if mods:
        out["py_modules_pkgs"] = [_upload(m) for m in mods]
        del out["py_modules"]
    return out


# ---------------------------------------------------------------- executor
_extract_lock = threading.Lock()


def _extract(worker, sha: str) -> str:
    """Fetch a package from the controller KV and extract it (cached per
    node in the session dir)."""
    from ray_tpu._private.rtconfig import CONFIG

    dest = os.path.join(CONFIG.session_dir, worker.session_id, "pkg", sha)
    done = dest + ".done"
    with _extract_lock:
        if os.path.exists(done):
            return dest
        rep = worker.kv("get", ns="pkg", key=sha)
        blob = rep["value"]
        if blob is None:
            raise RuntimeError(f"runtime_env package {sha} not found in KV")
        os.makedirs(dest, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(bytes(blob))) as zf:
            zf.extractall(dest)
        open(done, "w").close()
        return dest


def apply(worker, runtime_env: dict | None):
    """Executor side: activate working_dir/py_modules for the current task
    or actor. Returns an undo callable (pooled workers restore between
    tasks; dedicated workers never call it)."""
    if not runtime_env:
        return lambda: None
    undo_ops: list = []
    wd_sha = runtime_env.get("working_dir_pkg")
    if wd_sha:
        path = _extract(worker, wd_sha)
        prev_cwd = os.getcwd()
        os.chdir(path)
        sys.path.insert(0, path)
        undo_ops.append(lambda: (os.chdir(prev_cwd),
                                 path in sys.path and sys.path.remove(path)))
    for sha in runtime_env.get("py_modules_pkgs") or ():
        path = _extract(worker, sha)
        sys.path.insert(0, path)
        undo_ops.append(lambda p=path: p in sys.path and sys.path.remove(p))

    def undo():
        for op in reversed(undo_ops):
            try:
                op()
            except Exception:
                pass

    return undo
