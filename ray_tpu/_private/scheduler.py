"""Cluster scheduling policies.

Parity target: reference raylet/scheduling/policy/ — hybrid (default: pack
until a node's utilization exceeds a threshold, then spread;
hybrid_scheduling_policy.h:50), spread, node-affinity, placement-group bundle
policies, composed like composite_scheduling_policy.h. Here the controller is
the single scheduler (GCS-side scheduling), which suits TPU pods: slices are
long-lived gang resources, so central decisions beat distributed spillback.
"""

from __future__ import annotations

from typing import Optional

from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.task_spec import SchedulingStrategy


class NodeState:
    __slots__ = ("node_id", "address", "total", "available", "liveness",
                 "last_beat", "labels", "draining", "shm_used", "incarnation",
                 "suspect_since")

    def __init__(self, node_id: str, address: tuple, total: ResourceSet, labels: dict | None = None):
        self.node_id = node_id
        self.address = address
        self.total = total
        self.available = total.copy()
        # Liveness state machine (reference GcsNodeManager + health checks,
        # but with an explicit SUSPECT stage): ALIVE -> SUSPECT on
        # connection loss, SUSPECT -> ALIVE on re-registration within the
        # grace window, SUSPECT -> DEAD on expiry. DEAD is terminal for
        # this NodeState (a returning agent gets a fresh one).
        self.liveness = "ALIVE"  # ALIVE | SUSPECT | DEAD
        self.last_beat = 0.0
        self.labels = labels or {}
        # Draining (autoscaler scale-down handshake): schedulable = False.
        # The node keeps running what it has; nothing new lands on it.
        self.draining = False
        # Heartbeat-reported shm-resident bytes (spilled blocks excluded).
        self.shm_used = 0
        # Controller-minted, monotonically increasing per node_id: fences
        # messages and connection-close events from a previous life of
        # this node (a zombie agent can never mutate current state).
        self.incarnation = 0
        self.suspect_since = 0.0

    @property
    def alive(self) -> bool:
        """Schedulable / trusted-for-accounting. SUSPECT nodes are frozen:
        not schedulable, leases and actors kept but nothing new lands."""
        return self.liveness == "ALIVE"

    def utilization(self) -> float:
        scores = []
        for k, tot in self.total.raw().items():
            if tot <= 0:
                continue
            avail = self.available.raw().get(k, 0)
            scores.append(1.0 - avail / tot)
        return max(scores) if scores else 0.0


def pick_node(
    demand: ResourceSet,
    strategy: SchedulingStrategy,
    nodes: dict[str, NodeState],
    pg_bundles: Optional[dict] = None,
    preferred: Optional[dict] = None,
) -> Optional[str]:
    """Return node_id to run on, or None if nothing is feasible right now.

    `preferred` maps node_id -> argument bytes already resident there
    (locality, reference dependency_manager.h + the hybrid policy's
    locality preference): a DEFAULT-strategy task runs where its biggest
    arguments live when that node is feasible."""
    alive = {nid: n for nid, n in nodes.items() if n.alive and not n.draining}
    if not alive:
        return None

    if preferred and strategy.kind == "DEFAULT":
        best = None
        for nid, nbytes in preferred.items():
            n = alive.get(nid)
            if n is not None and n.available.fits(demand):
                if best is None or nbytes > best[1]:
                    best = (nid, nbytes)
        if best is not None:
            return best[0]

    if strategy.kind == "PLACEMENT_GROUP" and pg_bundles is not None:
        # Bundles carry their own reserved resources on a pinned node.
        return _pick_pg_node(demand, strategy, pg_bundles)

    if strategy.kind == "NODE_AFFINITY":
        node = alive.get(strategy.node_id)
        if node is not None and node.available.fits(demand):
            return node.node_id
        if strategy.soft:
            return _hybrid(demand, alive)
        # hard affinity: infeasible until that node frees up (or forever)
        return None

    if strategy.kind == "SPREAD":
        feasible = [n for n in alive.values() if n.available.fits(demand)]
        if not feasible:
            return None
        return min(feasible, key=lambda n: (n.utilization(), n.node_id)).node_id

    return _hybrid(demand, alive)


def _hybrid(demand: ResourceSet, alive: dict[str, NodeState]) -> Optional[str]:
    """Pack onto low-id nodes until utilization crosses the spread threshold,
    then prefer the least-utilized node (reference hybrid_scheduling_policy)."""
    feasible = [n for n in alive.values() if n.available.fits(demand)]
    if not feasible:
        return None
    thresh = CONFIG.scheduler_spread_threshold
    below = [n for n in feasible if n.utilization() <= thresh]
    if below:
        return min(below, key=lambda n: n.node_id).node_id
    return min(feasible, key=lambda n: (n.utilization(), n.node_id)).node_id


def _pick_pg_node(demand: ResourceSet, strategy: SchedulingStrategy, pg_bundles: dict) -> Optional[str]:
    """pg_bundles: {(pg_id, bundle_idx): {"node": nid, "available": ResourceSet}}"""
    if strategy.pg_bundle_index >= 0:
        key = (strategy.pg_id, strategy.pg_bundle_index)
        b = pg_bundles.get(key)
        if b is not None and b["available"].fits(demand):
            return b["node"]
        return None
    for (pgid, _idx), b in sorted(pg_bundles.items(), key=lambda kv: kv[0][1]):
        if pgid == strategy.pg_id and b["available"].fits(demand):
            return b["node"]
    return None
