"""Lightweight asyncio RPC transport for the control/object plane.

Parity target: the reference's gRPC scaffolding (src/ray/rpc/, 6k LoC C++) —
request/response services plus one-way pushes. grpcio is not a baked-in dep of
this image, so the transport is asyncio TCP with length-prefixed pickle5
frames (out-of-band buffers => large tensors are written to the socket without
an extra pickle copy).

Frame layout (everything little-endian):
    [8B total_len][4B nbufs][8B header_len][header pickle][ (8B len, raw)* ]
"""

from __future__ import annotations

import asyncio
import socket
import struct
import threading
import time
import traceback
import weakref
from typing import Awaitable, Callable, Optional

from ray_tpu._private.serialization import dumps_oob, loads_oob

_HDR = struct.Struct("<Q")


# Write-coalescing knobs live in the rtconfig registry like every other
# runtime flag (env RT_RPC_COALESCE / RT_RPC_WBUF_HIGH_BYTES /
# RT_RPC_JOIN_BYTES, or init(_system_config={...}) — the resolved table is
# propagated cluster-wide at registration). Connections cache the values at
# construction; see the README "Transport" section.
from ray_tpu._private.rtconfig import CONFIG as _CONFIG  # noqa: E402


def _set_nodelay(writer) -> None:
    """Assert TCP_NODELAY on TCP sockets. asyncio sets it by default on TCP
    transports, but the coalesced write path depends on it (a batched burst
    must not sit in the Nagle window), so assert it explicitly."""
    try:
        sock = writer.get_extra_info("socket")
        if sock is not None and sock.family in (socket.AF_INET,
                                                socket.AF_INET6):
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except Exception:
        pass


# ------------------------------------------------------- fault injection
# Deterministic chaos layer for tests (reference: Ray's testing_asio
# delay/failure injection, src/ray/common/test/testing_asio.h role).
# Connections carry a `label` naming their class ("node" for the
# controller<->agent link, "lease" for worker<->worker lease pipes, ...);
# rules match (label, direction, method) and apply on deterministic frame
# schedules. The transport pays ONE module-global None check per frame when
# injection is off; nothing else changes.


class FaultRule:
    """One injection rule. Frames are counted per rule (under a lock, so
    the schedule is deterministic): the first `after` matching frames pass
    untouched, the next `times` (None = all) get `action` applied.

    Actions: "drop" (frame vanishes; LATER frames still flow), "delay"
    (frame waits `delay_s`), "dup" (frame is delivered twice), "sever" (the
    connection is closed as if the TCP link reset — both sides observe a
    normal close), "hang" (the matched frame — and, per FIFO link
    semantics, everything behind it — is held FOREVER while the socket
    stays healthy: the silent-stall chaos primitive; neither side observes
    a close, calls never resolve)."""

    __slots__ = ("label", "action", "direction", "methods", "after", "times",
                 "delay_s", "match", "hits", "applied")

    def __init__(self, label, action, direction="both", methods=None,
                 after=0, times=None, delay_s=0.0, match=None):
        assert action in ("drop", "delay", "dup", "sever", "hang"), action
        assert direction in ("send", "recv", "both"), direction
        self.label = label
        self.action = action
        self.direction = direction
        self.methods = set(methods) if methods else None
        self.after = after
        self.times = times
        self.delay_s = delay_s
        self.match = match  # optional fn(msg_dict) -> bool
        self.hits = 0      # matching frames seen (before after/times gating)
        self.applied = 0   # frames the action actually hit


class FaultInjector:
    """Registry of live connections + active fault rules (tests only).

    Enable with `enable_fault_injection()` (or RT_FAULT_INJECTION=1 /
    `_system_config={"fault_injection": True}`) BEFORE the connections
    under test are created; disable with `disable_fault_injection()`.
    `stats` counts applied actions so tests can assert the schedule fired.
    """

    def __init__(self):
        self._conns: "weakref.WeakSet" = weakref.WeakSet()
        self._rules: list[FaultRule] = []
        self._lock = threading.Lock()
        self.stats: dict[str, int] = {}

    # -- connection registry ----------------------------------------------
    def track(self, conn) -> None:
        # Connections register from their event-loop threads while tests
        # iterate from the main thread: both sides take the lock.
        with self._lock:
            self._conns.add(conn)

    def connections(self, label: str | None = None) -> list:
        with self._lock:
            conns = list(self._conns)
        return [c for c in conns
                if not c.closed
                and (label is None or getattr(c, "label", None) == label)]

    def sever(self, label: str | None = None, match=None,
              count: int | None = None) -> int:
        """Close matching live connections (a simulated TCP reset): both
        endpoints observe an ordinary connection close. `match` further
        filters on the connection object (e.g. by conn.meta["node_id"]).
        Returns how many connections were severed. Callable from any
        thread — the close is marshalled onto each connection's loop."""
        n = 0
        for conn in self.connections(label):
            if match is not None and not match(conn):
                continue
            self.sever_conn(conn)
            n += 1
            if count is not None and n >= count:
                break
        with self._lock:
            self.stats["sever"] = self.stats.get("sever", 0) + n
        return n

    @staticmethod
    def sever_conn(conn) -> None:
        loop = getattr(conn, "loop", None)
        if loop is not None and loop.is_running():
            asyncio.run_coroutine_threadsafe(conn.close(), loop)
        else:  # not started yet / loop gone: best-effort direct close
            conn.closed = True

    # -- rules -------------------------------------------------------------
    def add_rule(self, label: str | None, action: str, *, direction="both",
                 methods=None, after: int = 0, times: int | None = None,
                 delay_s: float = 0.0, match=None) -> FaultRule:
        rule = FaultRule(label, action, direction, methods, after, times,
                         delay_s, match)
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove_rule(self, rule: FaultRule) -> None:
        with self._lock:
            if rule in self._rules:
                self._rules.remove(rule)

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
            self.stats.clear()

    def pick(self, conn, direction: str, msg: dict) -> Optional[FaultRule]:
        """First rule whose filter matches AND whose after/times schedule
        admits this frame. Counting happens under the lock, so a schedule
        like after=2,times=1 hits exactly the third matching frame."""
        if not self._rules:
            return None
        label = getattr(conn, "label", None)
        with self._lock:
            for r in self._rules:
                if r.label is not None and r.label != label:
                    continue
                if r.direction != "both" and r.direction != direction:
                    continue
                if r.methods is not None and msg.get("m") not in r.methods:
                    continue
                if r.match is not None and not r.match(msg):
                    continue
                r.hits += 1
                if r.hits <= r.after:
                    continue
                if r.times is not None and r.applied >= r.times:
                    continue
                r.applied += 1
                self.stats[r.action] = self.stats.get(r.action, 0) + 1
                return r
        return None


_INJECTOR: Optional[FaultInjector] = None


def enable_fault_injection() -> FaultInjector:
    global _INJECTOR
    if _INJECTOR is None:
        _INJECTOR = FaultInjector()
    return _INJECTOR


def disable_fault_injection() -> None:
    global _INJECTOR
    _INJECTOR = None


def fault_injector() -> Optional[FaultInjector]:
    return _INJECTOR


import os as _os  # noqa: E402

if _os.environ.get("RT_FAULT_INJECTION", "").lower() in ("1", "true", "yes"):
    enable_fault_injection()


# ----------------------------------------------------------- flight recorder
# Frame-level hook for the stall watchdog's flight recorder (see
# _private/watchdog.py): records "rpc_send"/"rpc_recv" events with the frame
# method. None (the default) keeps the hot path at exactly one module-global
# check per frame — the same zero-cost-when-off pattern as _INJECTOR.
_FLIGHT = None


def set_flight_hook(fn) -> None:
    global _FLIGHT
    _FLIGHT = fn


# ------------------------------------------------------------- trace hook
# Frame-level hook for the distributed tracing plane (see
# _private/tracing.py): fires ("rpc_send"/"rpc_recv", method) per frame and
# ("rpc_call", method, rtt_seconds) per completed request round trip. None
# (the default — RT_TRACING unset) keeps the hot path at exactly one
# module-global check per frame, the same zero-cost-when-off pattern as
# _INJECTOR and _FLIGHT. The hook itself discards events outside a sampled
# trace context, so an armed-but-unsampled frame costs one contextvar read.
_TRACE = None


def set_trace_hook(fn) -> None:
    global _TRACE
    _TRACE = fn


async def _hang_forever():
    """Park this coroutine permanently (injected 'hang': the frame — and the
    FIFO stream behind it — never moves, but the socket stays open)."""
    await asyncio.Event().wait()


class RpcError(Exception):
    pass


def _log_push_failure(f):
    """Done-callback for fire-and-forget pushes: peer-close races are benign,
    anything else (unpicklable payload, write error) must be surfaced — the
    consumer of the lost message would otherwise just hang."""
    if f.cancelled():
        return
    exc = f.exception()
    if exc is not None and not isinstance(
            exc, (ConnectionClosed, ConnectionResetError, BrokenPipeError)):
        import logging

        logging.getLogger(__name__).warning("fire-and-forget push failed: %r", exc)


class ConnectionClosed(RpcError):
    pass


class RemoteCallError(RpcError):
    def __init__(self, method: str, traceback_str: str):
        self.method = method
        self.traceback_str = traceback_str
        super().__init__(f"RPC {method} failed remotely:\n{traceback_str}")


def _encode(msg: dict) -> list:
    header, buffers = dumps_oob(msg)
    parts = [struct.pack("<IQ", len(buffers), len(header)), header]
    for b in buffers:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    total = sum(len(p) for p in parts)
    return [_HDR.pack(total), *parts]


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionClosed(str(e)) from None


async def _read_msg(reader: asyncio.StreamReader) -> dict:
    (total,) = _HDR.unpack(await _read_exact(reader, 8))
    payload = await _read_exact(reader, total)
    mv = memoryview(payload)
    nbufs, hlen = struct.unpack_from("<IQ", mv, 0)
    off = 12
    header = mv[off : off + hlen]
    off += hlen
    buffers = []
    for _ in range(nbufs):
        (blen,) = struct.unpack_from("<Q", mv, off)
        off += 8
        buffers.append(mv[off : off + blen])
        off += blen
    return loads_oob(bytes(header), buffers)


class Connection:
    """One bidirectional peer link. Both sides can issue requests and pushes."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._wlock = asyncio.Lock()
        # Adaptive frame coalescing (reference: gRPC's writev-style batched
        # stream writes): _write appends encoded frames to _wbuf; ONE
        # flusher per burst writes everything buffered and drains once.
        # Strict per-connection FIFO is preserved (appends happen in _write
        # call order, the single flusher writes in append order).
        self._coalesce = _CONFIG.rpc_coalesce
        self._whigh = _CONFIG.rpc_wbuf_high_bytes
        self._wjoin = _CONFIG.rpc_join_bytes
        self._wbuf: list = []  # bytes/memoryview parts + float delay markers
        self._wbuf_bytes = 0
        self._wflushing = False
        self._wdrain_evt: Optional[asyncio.Event] = None
        self.on_request: Optional[Callable[["Connection", str, dict], Awaitable]] = None
        self.on_push: Optional[Callable[["Connection", str, dict], Awaitable]] = None
        self.on_close: Optional[Callable[["Connection"], None]] = None
        self.closed = False
        self.meta: dict = {}  # server-side: who is this peer (set by register)
        self.label: Optional[str] = None  # fault-injection connection class
        self._read_task: Optional[asyncio.Task] = None
        self.loop: Optional[asyncio.AbstractEventLoop] = None

    def start(self):
        self.loop = asyncio.get_running_loop()
        if _INJECTOR is not None:
            _INJECTOR.track(self)
        self._read_task = asyncio.ensure_future(self._read_loop())

    @property
    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None

    async def _write(self, msg: dict):
        # Fault injection applies to the LOGICAL frame here, before any
        # coalescing: drop removes exactly this frame from the stream, dup
        # enqueues it twice, delay inserts a hold-the-line marker, sever
        # kills the connection (frames already buffered may be lost with it,
        # like a TCP reset).
        repeat, delay = 1, 0.0
        if _FLIGHT is not None:
            _FLIGHT("rpc_send", msg.get("m") or msg["k"])
        if _TRACE is not None:
            _TRACE("rpc_send", msg.get("m") or msg["k"])
        if _INJECTOR is not None:
            rule = _INJECTOR.pick(self, "send", msg)
            if rule is not None:
                if rule.action == "drop":
                    return
                if rule.action == "delay":
                    delay = rule.delay_s
                elif rule.action == "hang":
                    # Infinite delay, NOT a close: the frame (and the FIFO
                    # stream behind it) wedges while the socket stays
                    # healthy — the silent-stall primitive.
                    delay = float("inf")
                elif rule.action == "dup":
                    repeat = 2
                elif rule.action == "sever":
                    try:
                        self.writer.close()
                    except Exception:
                        pass
                    raise ConnectionClosed("fault injection: connection severed")
        parts = _encode(msg)
        if not self._coalesce:
            # Legacy path (RT_RPC_COALESCE=0): one drain per frame.
            async with self._wlock:
                if delay == float("inf"):
                    await _hang_forever()
                if delay:
                    # Sleep INSIDE the write lock: a delayed frame must hold
                    # up younger frames like a slow link would —
                    # per-connection reordering is a fault TCP cannot
                    # produce.
                    await asyncio.sleep(delay)
                for _ in range(repeat):
                    for p in parts:
                        self.writer.write(p)
                await self.writer.drain()
            return
        if self.closed:
            raise ConnectionClosed("connection closed")
        if delay:
            # float() pins the flusher's delay-marker type check even when
            # a rule was built with an int delay_s.
            self._wbuf.append(float(delay))
        n = 0
        for p in parts:
            n += len(p)
        for _ in range(repeat):
            self._wbuf.extend(parts)
        self._wbuf_bytes += n * repeat
        if not self._wflushing:
            self._wflushing = True
            asyncio.ensure_future(self._a_wflush())
        if self._wbuf_bytes >= self._whigh:
            # Backpressure: park until the flusher catches up (the legacy
            # path got the same bound from its per-frame drain).
            while self._wbuf_bytes >= self._whigh and not self.closed:
                if self._wdrain_evt is None:
                    self._wdrain_evt = asyncio.Event()
                self._wdrain_evt.clear()
                await self._wdrain_evt.wait()

    async def _a_wflush(self):
        """Single writer per burst: drains whatever accumulated while the
        previous socket write was in flight — frames buffered by N
        concurrent _write()s ride one write+drain."""
        w = self.writer
        try:
            while True:
                buf = self._wbuf
                if not buf:
                    self._wflushing = False
                    return
                self._wbuf = []
                self._wbuf_bytes = 0
                if self._wdrain_evt is not None:
                    self._wdrain_evt.set()
                small: list = []
                small_n = 0
                for item in buf:
                    if type(item) is float:
                        # Injected delay marker: flush everything older,
                        # then hold the line — younger frames wait behind
                        # the delayed one like on a slow link. An infinite
                        # marker (injected 'hang') parks the flusher for
                        # good with the connection still open.
                        if small:
                            w.write(small[0] if len(small) == 1
                                    else b"".join(small))
                            small, small_n = [], 0
                        await w.drain()
                        if item == float("inf"):
                            await _hang_forever()
                        await asyncio.sleep(item)
                        continue
                    if len(item) <= self._wjoin:
                        small.append(item)
                        small_n += len(item)
                        if small_n >= self._whigh:
                            w.write(b"".join(small))
                            small, small_n = [], 0
                    else:
                        # Large part (zero-copy tensor buffer): write
                        # uncopied, flanked by the joined small parts.
                        if small:
                            w.write(small[0] if len(small) == 1
                                    else b"".join(small))
                            small, small_n = [], 0
                        w.write(item)
                if small:
                    w.write(small[0] if len(small) == 1 else b"".join(small))
                await w.drain()
        except (ConnectionResetError, BrokenPipeError, ConnectionClosed,
                OSError, asyncio.CancelledError):
            pass
        except Exception:
            traceback.print_exc()
        # Write side died under buffered frames: surface via the normal
        # close path and wake writers parked on backpressure.
        self.closed = True
        self._wflushing = False
        self._wbuf.clear()
        self._wbuf_bytes = 0
        if self._wdrain_evt is not None:
            self._wdrain_evt.set()
        try:
            w.close()
        except Exception:
            pass

    async def call(self, method: str, _timeout: float | None = None, **payload):
        # Fail fast on a dead connection: the read loop already rejected
        # and CLEARED _pending, so a future registered now would never
        # resolve — the caller would await forever (observed: a lease
        # request wedging its class's `requesting` flag permanently after
        # a controller restart).
        if self.closed:
            raise ConnectionClosed("connection already closed")
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        tr = _TRACE
        t0 = time.monotonic() if tr is not None else 0.0
        try:
            await self._write({"k": "req", "id": rid, "m": method, "a": payload})
            if self.closed and not fut.done():
                # Raced the close between registration and the write (the
                # reader's sweep may have missed this future).
                raise ConnectionClosed("connection closed during call")
            if _timeout is not None:
                return await asyncio.wait_for(fut, _timeout)
            return await fut
        finally:
            self._pending.pop(rid, None)
            if tr is not None:
                tr("rpc_call", method, time.monotonic() - t0)

    async def call_start(self, method: str, **payload) -> asyncio.Future:
        """Write a request and return the reply future WITHOUT awaiting it.

        Lets a caller serialize request *ordering* (the frame is queued on
        the connection's FIFO write buffer before this returns, and the
        single flusher writes strictly in queue order) while overlapping
        many in-flight replies — the mechanism
        behind ordered-but-pipelined actor calls (reference: sequence numbers
        in core_worker/transport/sequential_actor_submit_queue.h).
        The caller must consume the future (and pop it from _pending on error).
        """
        if self.closed:
            raise ConnectionClosed("connection already closed")
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._write({"k": "req", "id": rid, "m": method, "a": payload})
        except Exception:
            self._pending.pop(rid, None)
            raise
        if self.closed and not fut.done():
            self._pending.pop(rid, None)
            raise ConnectionClosed("connection closed during call")
        def _done(f, rid=rid):
            self._pending.pop(rid, None)
        fut.add_done_callback(_done)
        return fut

    async def push(self, method: str, **payload):
        await self._write({"k": "push", "m": method, "a": payload})

    def push_threadsafe(self, method: str, **payload):
        """Fire-and-forget push usable from ANY thread. Enqueued onto the
        connection's loop via call_soon_threadsafe, which is FIFO per calling
        thread — so pushes issued before a later call() from the same thread
        are written to the socket first (the ordering the put->submit fast
        path relies on). Saves the ~2 thread handoffs of io.run(push(...))."""
        if self.loop is None:
            raise RpcError("connection not started")
        fut = asyncio.run_coroutine_threadsafe(self.push(method, **payload), self.loop)
        fut.add_done_callback(_log_push_failure)

    async def _handle_request(self, msg: dict):
        rid = msg["id"]
        try:
            if self.on_request is None:
                raise RpcError("no request handler installed")
            value = await self.on_request(self, msg["m"], msg["a"])
            reply = {"k": "rep", "id": rid, "ok": True, "v": value}
        except Exception:
            reply = {"k": "rep", "id": rid, "ok": False, "m": msg["m"], "v": traceback.format_exc()}
        try:
            await self._write(reply)
        except (ConnectionClosed, ConnectionResetError, BrokenPipeError):
            pass

    def _dispatch_msg(self, msg: dict):
        kind = msg["k"]
        if kind == "req":
            asyncio.ensure_future(self._handle_request(msg))
        elif kind == "rep":
            fut = self._pending.get(msg["id"])
            if fut is not None and not fut.done():
                if msg["ok"]:
                    fut.set_result(msg["v"])
                else:
                    fut.set_exception(RemoteCallError(msg.get("m", "?"), msg["v"]))
        elif kind == "push":
            if self.on_push is not None:
                asyncio.ensure_future(self.on_push(self, msg["m"], msg["a"]))

    async def _read_loop(self):
        try:
            while True:
                msg = await _read_msg(self.reader)
                if _FLIGHT is not None:
                    _FLIGHT("rpc_recv", msg.get("m") or msg["k"])
                if _TRACE is not None:
                    _TRACE("rpc_recv", msg.get("m") or msg["k"])
                if _INJECTOR is not None:
                    rule = _INJECTOR.pick(self, "recv", msg)
                    if rule is not None:
                        if rule.action == "drop":
                            continue
                        if rule.action == "hang":
                            # Hold the read loop (and every later frame on
                            # this FIFO link) forever; the socket stays open.
                            await _hang_forever()
                        if rule.action == "delay":
                            await asyncio.sleep(rule.delay_s)
                        elif rule.action == "sever":
                            raise ConnectionClosed(
                                "fault injection: connection severed")
                        elif rule.action == "dup":
                            self._dispatch_msg(msg)
                self._dispatch_msg(msg)
        except (ConnectionClosed, asyncio.CancelledError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionClosed("peer went away"))
            self._pending.clear()
            if self._wdrain_evt is not None:
                self._wdrain_evt.set()  # unblock writers parked on backpressure
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close is not None:
                try:
                    self.on_close(self)
                except Exception:
                    traceback.print_exc()

    async def close(self):
        if self._read_task is not None:
            self._read_task.cancel()
        # Graceful close drains frames _write already accepted: with
        # coalescing, push() returns once the frame is buffered, so a
        # push-then-close sequence (e.g. a worker's final task_done before
        # disconnect) must not drop the buffered frame. Bounded wait — a
        # dead peer can't hold the close hostage. Best-effort only: the
        # cancelled read task's teardown may set `closed` first and win
        # the race. A caller that NEEDS every buffered frame delivered
        # must ack at the protocol layer before closing (the way
        # PushStreamWriter awaits its s_close reply) — reordering this
        # drain ahead of the cancel leaves the connection half-open for
        # up to 2s, which was observed to race the worker-death path into
        # lost object-fetch wakeups (chaos shuffle test hang).
        if (self._wbuf or self._wflushing) and not self.closed:
            try:
                await asyncio.wait_for(self._a_wait_flushed(), 2.0)
            except Exception:
                pass
        self.closed = True
        self._wbuf.clear()
        self._wbuf_bytes = 0
        if self._wdrain_evt is not None:
            self._wdrain_evt.set()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass

    async def _a_wait_flushed(self):
        while (self._wbuf or self._wflushing) and not self.closed:
            await asyncio.sleep(0.005)


def _uds_dir() -> Optional[str]:
    """Per-user 0700 directory for unix sockets (round-2 advisor finding:
    predictable world-writable /tmp paths let another local user pre-create a
    socket and serve pickled replies = code execution; reference Ray keeps
    sockets in a per-session user-owned dir). Both the server (create) and the
    client (connect) verify the directory is a non-symlink dir owned by this
    uid with mode 0700 — anything else disables the UDS fast path (TCP-only
    is always correct)."""
    import os
    import stat

    path = f"/tmp/rt_uds_{os.geteuid()}"
    try:
        os.mkdir(path, 0o700)
    except FileExistsError:
        pass
    except OSError:
        return None
    try:
        st = os.lstat(path)
    except OSError:
        return None
    if (not stat.S_ISDIR(st.st_mode) or st.st_uid != os.geteuid()
            or stat.S_IMODE(st.st_mode) != 0o700):
        return None
    return path


def _uds_path(port: int) -> Optional[str]:
    d = _uds_dir()
    if d is None:
        return None
    return f"{d}/{port}.sock"


_created_socks: list[str] = []


def cleanup_sockets():
    """Unlink this process's unix-socket files. Registered atexit and called
    from SIGTERM handlers (workers are killed with terminate(), which would
    otherwise strand one socket file per worker in /tmp)."""
    import os

    while _created_socks:
        try:
            os.unlink(_created_socks.pop())
        except OSError:
            pass


import atexit as _atexit  # noqa: E402

_atexit.register(cleanup_sockets)


class RpcServer:
    """TCP server (+ a same-host unix-socket listener on the same logical
    port — loopback TCP costs measurably more per frame than UDS on the
    asyncio hot path); dispatches per-connection requests/pushes to
    handlers."""

    def __init__(
        self,
        on_request: Callable[[Connection, str, dict], Awaitable],
        on_push: Optional[Callable[[Connection, str, dict], Awaitable]] = None,
        on_close: Optional[Callable[[Connection], None]] = None,
        label: str | None = None,
    ):
        self._on_request = on_request
        self._on_push = on_push
        self._on_close = on_close
        # Fault-injection connection class stamped on every ACCEPTED
        # connection: client ends get theirs from connect(label=...), but
        # without this the server side of the same link is unaddressable
        # by FaultInjector rules (e.g. recv-direction drops on a stream
        # hub's inbound frames).
        self._label = label
        self._server: Optional[asyncio.AbstractServer] = None
        self._uds_server: Optional[asyncio.AbstractServer] = None
        self.connections: set = set()
        self.port: int = 0
        self.loop: Optional[asyncio.AbstractEventLoop] = None

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._accept, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        self.loop = asyncio.get_running_loop()
        _LOCAL_SERVERS[self.port] = self
        try:
            import os

            path = _uds_path(self.port)
            if path is None:
                raise OSError("no private uds dir")
            if os.path.exists(path):
                os.unlink(path)
            self._uds_server = await asyncio.start_unix_server(self._accept, path)
            os.chmod(path, 0o600)
            _created_socks.append(path)
        except Exception:
            self._uds_server = None  # TCP-only is always correct
        return self.port

    async def _accept(self, reader, writer):
        _set_nodelay(writer)
        conn = Connection(reader, writer)
        conn.label = self._label
        conn.on_request = self._on_request
        conn.on_push = self._on_push
        conn.on_close = self._conn_closed
        self.connections.add(conn)
        conn.start()

    def _conn_closed(self, conn: Connection):
        self.connections.discard(conn)
        if self._on_close is not None:
            self._on_close(conn)

    async def stop(self):
        if _LOCAL_SERVERS.get(self.port) is self:
            del _LOCAL_SERVERS[self.port]
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
        if self._uds_server is not None:
            self._uds_server.close()
            try:
                await self._uds_server.wait_closed()
            except Exception:
                pass
            import os

            path = _uds_path(self.port)
            if path is not None:
                try:
                    os.unlink(path)
                except OSError:
                    pass
        for conn in list(self.connections):
            await conn.close()


# port -> RpcServer hosted by THIS process. Lets connect() bypass sockets and
# serialization entirely for same-process peers (driver <-> controller <->
# head agent share one process in local mode — cf. bootstrap.HeadNode). The
# reference gets the same effect from its in-process CoreWorkerMemoryStore and
# direct C++ calls between colocated components.
_LOCAL_SERVERS: dict[int, "RpcServer"] = {}


class LocalConnection:
    """In-process peer link with Connection's API but no sockets/pickling.

    Messages are delivered as live Python objects via call_soon_threadsafe
    (FIFO per sending thread — same ordering contract as a socket write).
    Handlers MUST treat received payloads as read-only, which they already do
    for the RPC path (payloads there are fresh unpickled copies)."""

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop  # loop this endpoint's callbacks run on
        self.peer: Optional["LocalConnection"] = None
        self.on_request: Optional[Callable] = None
        self.on_push: Optional[Callable] = None
        self.on_close: Optional[Callable] = None
        self.closed = False
        self.meta: dict = {}
        self.label: Optional[str] = None  # fault-injection connection class
        # Injected 'hang': once set, every later message is swallowed
        # silently (it is "in the pipe" behind the held frame) while the
        # link still looks healthy — calls simply never resolve.
        self._hung = False
        if _INJECTOR is not None:
            _INJECTOR.track(self)

    @property
    def peername(self):
        return ("local", id(self.peer))

    # -- outgoing ---------------------------------------------------------
    def _deliver(self, kind: str, method: str, payload: dict, reply_to=None):
        peer = self.peer
        if peer is None or peer.closed:
            raise ConnectionClosed("local peer went away")
        if self._hung:
            return  # wedged behind a held frame; link still "healthy"
        if _FLIGHT is not None:
            _FLIGHT("rpc_send", method)
        if _TRACE is not None:
            _TRACE("rpc_send", method)
        if _INJECTOR is not None:
            # The in-process transport has no frames; model the message
            # itself as one (send direction only — there is no reader side).
            rule = _INJECTOR.pick(
                self, "send", {"k": kind, "m": method, "a": payload})
            if rule is not None:
                if rule.action == "drop":
                    if reply_to is not None:
                        loop, fut = reply_to
                        loop.call_soon_threadsafe(
                            _fut_set_exc, fut,
                            ConnectionClosed("fault injection: frame dropped"))
                    return
                if rule.action == "sever":
                    self._close_both()
                    raise ConnectionClosed(
                        "fault injection: connection severed")
                if rule.action == "hang":
                    self._hung = True
                    return  # this frame and everything after it wedge
                if rule.action == "delay":
                    peer.loop.call_soon_threadsafe(
                        peer.loop.call_later, rule.delay_s, peer._dispatch,
                        kind, method, payload, reply_to)
                    return
                if rule.action == "dup":
                    peer.loop.call_soon_threadsafe(
                        peer._dispatch, kind, method, payload, None)
        peer.loop.call_soon_threadsafe(peer._dispatch, kind, method, payload, reply_to)

    async def call(self, method: str, _timeout: float | None = None, **payload):
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        tr = _TRACE
        t0 = time.monotonic() if tr is not None else 0.0
        self._deliver("req", method, payload, (asyncio.get_running_loop(), fut))
        try:
            if _timeout is not None:
                return await asyncio.wait_for(fut, _timeout)
            return await fut
        finally:
            if tr is not None:
                tr("rpc_call", method, time.monotonic() - t0)

    async def call_start(self, method: str, **payload) -> asyncio.Future:
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._deliver("req", method, payload, (asyncio.get_running_loop(), fut))
        return fut

    async def push(self, method: str, **payload):
        self._deliver("push", method, payload)

    def push_threadsafe(self, method: str, **payload):
        self._deliver("push", method, payload)

    # -- incoming (runs on self.loop) -------------------------------------
    def _dispatch(self, kind: str, method: str, payload: dict, reply_to):
        if self.closed:
            if reply_to is not None:
                loop, fut = reply_to
                loop.call_soon_threadsafe(_fut_set_exc, fut, ConnectionClosed("local peer closed"))
            return
        asyncio.ensure_future(self._run_handler(kind, method, payload, reply_to))

    async def _run_handler(self, kind: str, method: str, payload: dict, reply_to):
        if kind == "push":
            if self.on_push is not None:
                try:
                    await self.on_push(self, method, payload)
                except Exception:
                    traceback.print_exc()
            return
        try:
            if self.on_request is None:
                raise RpcError("no request handler installed")
            value = await self.on_request(self, method, payload)
            err = None
        except Exception:
            value = None
            err = RemoteCallError(method, traceback.format_exc())
        if reply_to is None:
            return  # fault-injected duplicate of a request: reply discarded
        loop, fut = reply_to
        if err is None:
            loop.call_soon_threadsafe(_fut_set_result, fut, value)
        else:
            loop.call_soon_threadsafe(_fut_set_exc, fut, err)

    async def close(self):
        self._close_both()

    def _close_both(self):
        for end in (self, self.peer):
            if end is None or end.closed:
                continue
            end.closed = True
            if end.on_close is not None:
                end.loop.call_soon_threadsafe(_safe_on_close, end)


def _fut_set_result(fut, value):
    if not fut.done():
        fut.set_result(value)


def _fut_set_exc(fut, err):
    if not fut.done():
        fut.set_exception(err)


def _safe_on_close(end):
    try:
        end.on_close(end)
    except Exception:
        traceback.print_exc()


async def connect(
    host: str,
    port: int,
    on_request=None,
    on_push=None,
    on_close=None,
    timeout: float = 30.0,
    label: str | None = None,
) -> Connection:
    server = _LOCAL_SERVERS.get(port) if host in ("127.0.0.1", "localhost") else None
    if server is not None and server.loop is not None:
        client = LocalConnection(asyncio.get_running_loop())
        serv_end = LocalConnection(server.loop)
        client.peer, serv_end.peer = serv_end, client
        client.label = label
        client.on_request, client.on_push, client.on_close = on_request, on_push, on_close
        serv_end.label = server._label
        serv_end.on_request = server._on_request
        serv_end.on_push = server._on_push
        serv_end.on_close = server._conn_closed
        server.connections.add(serv_end)
        return client
    reader = writer = None
    if host in ("127.0.0.1", "localhost"):
        import os

        path = _uds_path(port)
        if path is not None and os.path.exists(path):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(path), timeout)
            except Exception:
                reader = writer = None  # fall back to TCP
    if reader is None:
        reader, writer = await asyncio.wait_for(asyncio.open_connection(host, port), timeout)
        _set_nodelay(writer)
    conn = Connection(reader, writer)
    conn.label = label
    conn.on_request = on_request
    conn.on_push = on_push
    conn.on_close = on_close
    conn.start()
    return conn


class EventLoopThread:
    """A dedicated asyncio loop in a daemon thread; sync code bridges via run().

    Parity note: plays the role of the reference's per-process asio io_service
    (src/ray/common/asio/) — all network IO for a process funnels through one
    event loop while user code stays synchronous.
    """

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        def _cancel_all():
            for t in asyncio.all_tasks(self.loop):
                t.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_cancel_all)
            self._thread.join(timeout=2.0)
        except Exception:
            pass
