"""Lightweight asyncio RPC transport for the control/object plane.

Parity target: the reference's gRPC scaffolding (src/ray/rpc/, 6k LoC C++) —
request/response services plus one-way pushes. grpcio is not a baked-in dep of
this image, so the transport is asyncio TCP with length-prefixed pickle5
frames (out-of-band buffers => large tensors are written to the socket without
an extra pickle copy).

Frame layout (everything little-endian):
    [8B total_len][4B nbufs][8B header_len][header pickle][ (8B len, raw)* ]
"""

from __future__ import annotations

import asyncio
import struct
import threading
import traceback
from typing import Awaitable, Callable, Optional

from ray_tpu._private.serialization import dumps_oob, loads_oob

_HDR = struct.Struct("<Q")


class RpcError(Exception):
    pass


class ConnectionClosed(RpcError):
    pass


class RemoteCallError(RpcError):
    def __init__(self, method: str, traceback_str: str):
        self.method = method
        self.traceback_str = traceback_str
        super().__init__(f"RPC {method} failed remotely:\n{traceback_str}")


def _encode(msg: dict) -> list:
    header, buffers = dumps_oob(msg)
    parts = [struct.pack("<IQ", len(buffers), len(header)), header]
    for b in buffers:
        parts.append(struct.pack("<Q", len(b)))
        parts.append(b)
    total = sum(len(p) for p in parts)
    return [_HDR.pack(total), *parts]


async def _read_exact(reader: asyncio.StreamReader, n: int) -> bytes:
    try:
        return await reader.readexactly(n)
    except (asyncio.IncompleteReadError, ConnectionResetError, BrokenPipeError) as e:
        raise ConnectionClosed(str(e)) from None


async def _read_msg(reader: asyncio.StreamReader) -> dict:
    (total,) = _HDR.unpack(await _read_exact(reader, 8))
    payload = await _read_exact(reader, total)
    mv = memoryview(payload)
    nbufs, hlen = struct.unpack_from("<IQ", mv, 0)
    off = 12
    header = mv[off : off + hlen]
    off += hlen
    buffers = []
    for _ in range(nbufs):
        (blen,) = struct.unpack_from("<Q", mv, off)
        off += 8
        buffers.append(mv[off : off + blen])
        off += blen
    return loads_oob(bytes(header), buffers)


class Connection:
    """One bidirectional peer link. Both sides can issue requests and pushes."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self.reader = reader
        self.writer = writer
        self._next_id = 0
        self._pending: dict[int, asyncio.Future] = {}
        self._wlock = asyncio.Lock()
        self.on_request: Optional[Callable[["Connection", str, dict], Awaitable]] = None
        self.on_push: Optional[Callable[["Connection", str, dict], Awaitable]] = None
        self.on_close: Optional[Callable[["Connection"], None]] = None
        self.closed = False
        self.meta: dict = {}  # server-side: who is this peer (set by register)
        self._read_task: Optional[asyncio.Task] = None

    def start(self):
        self._read_task = asyncio.ensure_future(self._read_loop())

    @property
    def peername(self):
        try:
            return self.writer.get_extra_info("peername")
        except Exception:
            return None

    async def _write(self, msg: dict):
        parts = _encode(msg)
        async with self._wlock:
            for p in parts:
                self.writer.write(p)
            await self.writer.drain()

    async def call(self, method: str, _timeout: float | None = None, **payload):
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._write({"k": "req", "id": rid, "m": method, "a": payload})
            if _timeout is not None:
                return await asyncio.wait_for(fut, _timeout)
            return await fut
        finally:
            self._pending.pop(rid, None)

    async def call_start(self, method: str, **payload) -> asyncio.Future:
        """Write a request and return the reply future WITHOUT awaiting it.

        Lets a caller serialize request *ordering* (the write happens before
        this returns) while overlapping many in-flight replies — the mechanism
        behind ordered-but-pipelined actor calls (reference: sequence numbers
        in core_worker/transport/sequential_actor_submit_queue.h).
        The caller must consume the future (and pop it from _pending on error).
        """
        self._next_id += 1
        rid = self._next_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            await self._write({"k": "req", "id": rid, "m": method, "a": payload})
        except Exception:
            self._pending.pop(rid, None)
            raise
        def _done(f, rid=rid):
            self._pending.pop(rid, None)
        fut.add_done_callback(_done)
        return fut

    async def push(self, method: str, **payload):
        await self._write({"k": "push", "m": method, "a": payload})

    async def _handle_request(self, msg: dict):
        rid = msg["id"]
        try:
            if self.on_request is None:
                raise RpcError("no request handler installed")
            value = await self.on_request(self, msg["m"], msg["a"])
            reply = {"k": "rep", "id": rid, "ok": True, "v": value}
        except Exception:
            reply = {"k": "rep", "id": rid, "ok": False, "m": msg["m"], "v": traceback.format_exc()}
        try:
            await self._write(reply)
        except (ConnectionClosed, ConnectionResetError, BrokenPipeError):
            pass

    async def _read_loop(self):
        try:
            while True:
                msg = await _read_msg(self.reader)
                kind = msg["k"]
                if kind == "req":
                    asyncio.ensure_future(self._handle_request(msg))
                elif kind == "rep":
                    fut = self._pending.get(msg["id"])
                    if fut is not None and not fut.done():
                        if msg["ok"]:
                            fut.set_result(msg["v"])
                        else:
                            fut.set_exception(RemoteCallError(msg.get("m", "?"), msg["v"]))
                elif kind == "push":
                    if self.on_push is not None:
                        asyncio.ensure_future(self.on_push(self, msg["m"], msg["a"]))
        except (ConnectionClosed, asyncio.CancelledError):
            pass
        except Exception:
            traceback.print_exc()
        finally:
            self.closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionClosed("peer went away"))
            self._pending.clear()
            try:
                self.writer.close()
            except Exception:
                pass
            if self.on_close is not None:
                try:
                    self.on_close(self)
                except Exception:
                    traceback.print_exc()

    async def close(self):
        if self._read_task is not None:
            self._read_task.cancel()
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except Exception:
            pass
        self.closed = True


def _uds_path(port: int) -> str:
    return f"/tmp/rt_uds_{port}.sock"


_created_socks: list[str] = []


def cleanup_sockets():
    """Unlink this process's unix-socket files. Registered atexit and called
    from SIGTERM handlers (workers are killed with terminate(), which would
    otherwise strand one socket file per worker in /tmp)."""
    import os

    while _created_socks:
        try:
            os.unlink(_created_socks.pop())
        except OSError:
            pass


import atexit as _atexit  # noqa: E402

_atexit.register(cleanup_sockets)


class RpcServer:
    """TCP server (+ a same-host unix-socket listener on the same logical
    port — loopback TCP costs measurably more per frame than UDS on the
    asyncio hot path); dispatches per-connection requests/pushes to
    handlers."""

    def __init__(
        self,
        on_request: Callable[[Connection, str, dict], Awaitable],
        on_push: Optional[Callable[[Connection, str, dict], Awaitable]] = None,
        on_close: Optional[Callable[[Connection], None]] = None,
    ):
        self._on_request = on_request
        self._on_push = on_push
        self._on_close = on_close
        self._server: Optional[asyncio.AbstractServer] = None
        self._uds_server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()
        self.port: int = 0

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self._server = await asyncio.start_server(self._accept, host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        try:
            import os

            path = _uds_path(self.port)
            if os.path.exists(path):
                os.unlink(path)
            self._uds_server = await asyncio.start_unix_server(self._accept, path)
            _created_socks.append(path)
        except Exception:
            self._uds_server = None  # TCP-only is always correct
        return self.port

    async def _accept(self, reader, writer):
        conn = Connection(reader, writer)
        conn.on_request = self._on_request
        conn.on_push = self._on_push
        conn.on_close = self._conn_closed
        self.connections.add(conn)
        conn.start()

    def _conn_closed(self, conn: Connection):
        self.connections.discard(conn)
        if self._on_close is not None:
            self._on_close(conn)

    async def stop(self):
        if self._server is not None:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass
        if self._uds_server is not None:
            self._uds_server.close()
            try:
                await self._uds_server.wait_closed()
            except Exception:
                pass
            import os

            try:
                os.unlink(_uds_path(self.port))
            except OSError:
                pass
        for conn in list(self.connections):
            await conn.close()


async def connect(
    host: str,
    port: int,
    on_request=None,
    on_push=None,
    on_close=None,
    timeout: float = 30.0,
) -> Connection:
    reader = writer = None
    if host in ("127.0.0.1", "localhost"):
        import os

        path = _uds_path(port)
        if os.path.exists(path):
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_unix_connection(path), timeout)
            except Exception:
                reader = writer = None  # fall back to TCP
    if reader is None:
        reader, writer = await asyncio.wait_for(asyncio.open_connection(host, port), timeout)
    conn = Connection(reader, writer)
    conn.on_request = on_request
    conn.on_push = on_push
    conn.on_close = on_close
    conn.start()
    return conn


class EventLoopThread:
    """A dedicated asyncio loop in a daemon thread; sync code bridges via run().

    Parity note: plays the role of the reference's per-process asio io_service
    (src/ray/common/asio/) — all network IO for a process funnels through one
    event loop while user code stays synchronous.
    """

    def __init__(self, name: str = "rt-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._started = threading.Event()
        self._thread.start()
        self._started.wait()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.call_soon(self._started.set)
        self.loop.run_forever()

    def run(self, coro, timeout: float | None = None):
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def spawn(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        def _cancel_all():
            for t in asyncio.all_tasks(self.loop):
                t.cancel()
            self.loop.call_soon(self.loop.stop)

        try:
            self.loop.call_soon_threadsafe(_cancel_all)
            self._thread.join(timeout=2.0)
        except Exception:
            pass
