"""TPU accelerator detection — TPU chips are first-class schedulable resources.

Parity target: reference python/ray/_private/accelerators/tpu.py:109
(TPUAcceleratorManager — detects chips via /dev/accel* & /dev/vfio
tpu.py:135-150, sets TPU_VISIBLE_CHIPS, knows pod topology, e.g.
get_num_workers_in_current_tpu_pod tpu.py:312). Unlike the reference — where
TPU support is one plugin among many — this runtime treats "TPU" like the
reference treats GPU, and additionally advertises slice-level gang resources
("TPU-<accel>-<topology>-head") so pod-scale jobs can be placed atomically.
"""

from __future__ import annotations

import glob
import os

TPU_RESOURCE = "TPU"


def num_tpu_chips() -> int:
    """Detect the number of TPU chips on this host."""
    env = os.environ.get("RT_NUM_TPUS") or os.environ.get("TPU_CHIPS")
    if env:
        return int(env)
    visible = os.environ.get("TPU_VISIBLE_CHIPS")
    if visible:
        return len([c for c in visible.split(",") if c.strip()])
    # Device-file probing, same sources as the reference (tpu.py:135-150).
    n = len(glob.glob("/dev/accel*"))
    if n == 0 and os.path.isdir("/dev/vfio"):
        n = len([f for f in os.listdir("/dev/vfio") if f != "vfio"])
    return n


def tpu_generation() -> str | None:
    """e.g. 'v5e' | 'v4' — from env (GKE sets TPU_ACCELERATOR_TYPE)."""
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5litepod-16"
    if accel:
        return accel.split("-")[0].replace("litepod", "5e").replace("v5lite", "v5e")
    return None


def tpu_pod_resources() -> dict[str, float]:
    """Extra pod-topology resources for this host (slice head marker etc.).
    Mirrors the reference's `TPU-{accel}-head` custom resource that lets a
    single task gang-own a pod slice (tpu.py get_current_pod_name/worker
    count)."""
    out: dict[str, float] = {}
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")
    worker_id = os.environ.get("TPU_WORKER_ID")
    if accel and (worker_id is None or worker_id == "0"):
        out[f"TPU-{accel}-head"] = 1.0
    return out


def host_resources(num_cpus: float | None = None, num_tpus: float | None = None) -> dict[str, float]:
    r: dict[str, float] = {}
    r["CPU"] = float(num_cpus) if num_cpus is not None else float(os.cpu_count() or 1)
    chips = num_tpus if num_tpus is not None else num_tpu_chips()
    if chips:
        r[TPU_RESOURCE] = float(chips)
        r.update(tpu_pod_resources())
    return r
