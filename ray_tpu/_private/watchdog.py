"""Stall detection: per-task progress beacons, a flight recorder, and the
warn -> dump -> kill escalation ladder (README "Stall detection & watchdogs").

The failure mode this closes is SILENT: a task spinning in user code, a
collective wedged on a sick peer, a worker alive with its socket open but
making no progress. None of the loud-failure machinery (connection-close
liveness, worker-death reports, lease failover) fires for these — the
reference runtime needs its health-check manager
(gcs_health_check_manager.cc) and per-attempt timeouts (task_manager.cc)
for exactly this reason.

Three pieces, all in-process and cheap enough to leave compiled in:

- **Progress beacons**: every executing task registers here (task_begin /
  task_end); user code can tick the beacon mid-task via
  `ray_tpu.util.report_progress()`, and runtime-level progress points
  (collective ring steps, streamed generator items) tick it too. "Progress"
  is a monotonic timestamp per executing thread.

- **Flight recorder**: a bounded ring of recent runtime events (task
  begin/end, collective enter/exit, RPC frame send/recv, progress reports).
  Recording is a deque append behind one enabled-flag check; the ring is
  dumped into the `StallReport` on escalation so the operator sees what the
  process was doing in the seconds before it went quiet.

- **Monitor thread** (`Watchdog`): wakes every beacon interval, measures
  each executing task's silence (now - last progress), and emits a
  structured `StallReport` through its callback as the task crosses
  RT_STALL_WARN_S / RT_STALL_DUMP_S / RT_STALL_KILL_S — each stage at most
  once per (task_id, attempt). The worker process never kills itself: the
  kill-stage report reaches the node agent, which captures stacks through
  its existing per-pid dump path, persists the flight dump through the
  storage plane, and fells the worker so the attempt fails over through the
  ordinary retry machinery.

All stages default OFF (0 = disabled); with every threshold unset the
monitor thread never starts and nothing beacons — behavior is byte-identical
to a watchdog-free build.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from ray_tpu._private.rtconfig import CONFIG

# The whole plane is ARMED only when a Watchdog with at least one enabled
# stage starts in this process. Unarmed (the default — every RT_STALL_*
# unset), task_begin/task_end/record are one module-global check and
# return: the n:n actor hot path pays nothing for the stall machinery.
_armed = False

# ------------------------------------------------------------ flight recorder
# Ring of (wall_time, kind, detail). While armed it costs one module-global
# check + a deque append per event; RT_FLIGHT_RECORDER_EVENTS=0 disables
# the ring even when armed.
_ring: Optional[deque] = None
_ring_lock = threading.Lock()


def _ensure_ring() -> Optional[deque]:
    global _ring
    if _ring is None:
        n = CONFIG.flight_recorder_events
        if n <= 0:
            return None
        with _ring_lock:
            if _ring is None:
                _ring = deque(maxlen=int(n))
    return _ring


def record(kind: str, detail: str = "") -> None:
    """Append one event to the flight recorder (no-op when unarmed)."""
    ring = _ring
    if ring is None:
        if not _armed:
            return
        ring = _ensure_ring()
        if ring is None:
            return
    ring.append((time.time(), kind, detail))


def flight_events(limit: int = 64) -> list:
    """Most recent `limit` recorded events, oldest first. Readers race
    RPC-thread appends; list(deque) can raise RuntimeError mid-mutation,
    so snapshotting retries — an escalation report must never be lost to
    a ring race."""
    ring = _ring
    if ring is None:
        return []
    for _ in range(4):
        try:
            evs = list(ring)
            return evs[-limit:]
        except RuntimeError:
            continue
    return []


def is_armed() -> bool:
    return _armed


# -------------------------------------------------------------- progress state
# One entry per thread currently executing a task: thread ident ->
# {"task_id", "name", "attempt", "kind", "started", "last_progress"}.
# Multiple entries exist on threaded/async actors; the monitor scans all.
_executing: dict[int, dict] = {}
_exec_lock = threading.Lock()
_local = threading.local()


def task_begin(task_id: str, name: str, attempt: int, kind: str,
               trace_id: str | None = None) -> None:
    if not _armed:
        return
    now = time.monotonic()
    st = {"task_id": task_id, "name": name, "attempt": attempt, "kind": kind,
          "started": now, "last_progress": now, "trace_id": trace_id}
    ident = threading.get_ident()
    _local.state = st
    with _exec_lock:
        _executing[ident] = st
    record("task_begin", f"{name} {task_id[:12]} a{attempt}")


def task_end(ok: bool = True) -> None:
    if not _armed:
        return
    ident = threading.get_ident()
    _local.state = None
    with _exec_lock:
        st = _executing.pop(ident, None)
    if st is not None:
        record("task_end", f"{st['name']} {st['task_id'][:12]} "
                           f"{'ok' if ok else 'err'}")


def report_progress(message: str | None = None) -> None:
    """Tick the current task's progress beacon (public:
    `ray_tpu.util.report_progress`). Call this from long-running user code
    so the stall watchdog knows the task is alive; a no-op outside a task
    (and when the watchdog plane is idle)."""
    st = getattr(_local, "state", None)
    if st is not None:
        st["last_progress"] = time.monotonic()
    if message:
        record("progress", message)


def progress_slice_s(default: float = 0.25) -> float:
    """Wait-slice length for loops that block on EXTERNAL progress
    (compiled-DAG channel reads, armed collective recvs): while the stall
    plane is armed, indefinite waits must be chopped into slices shorter
    than the beacon interval with a `report_progress()` tick per slice, so
    an idle wait is never mistaken for a stalled task. Unarmed, callers
    keep their own (longer) default — the tick is a no-op anyway."""
    if not _armed:
        return default
    try:
        return max(0.05, min(default,
                             float(CONFIG.stall_beacon_interval_s) / 2.0))
    except Exception:
        return default


def executing_snapshot() -> list[dict]:
    """Copies of every executing-task state (monitor + beacon source)."""
    with _exec_lock:
        return [dict(st) for st in _executing.values()]


# --------------------------------------------------------------- stall report
def stages() -> dict[str, float]:
    """Enabled escalation thresholds ({} = escalation fully disabled)."""
    out = {}
    for stage, flag in (("warn", CONFIG.stall_warn_s),
                        ("dump", CONFIG.stall_dump_s),
                        ("kill", CONFIG.stall_kill_s)):
        if flag and flag > 0:
            out[stage] = float(flag)
    return out


def enabled() -> bool:
    return bool(stages())


def default_flight_dir(session_id: str) -> str:
    return os.path.join(CONFIG.session_dir, session_id, "flight")


def build_report(st: dict, stage: str, *, worker_id: str, node_id: str,
                 pid: int, session_id: str, silence_s: float,
                 reason: str | None = None) -> dict:
    """One structured StallReport — the unit the agent forwards, the
    controller aggregates (`util.state.list_stalls`), and the storage plane
    persists under <flight_dir>/ on dump/kill escalation."""
    return {
        "scope": "task",
        "stage": stage,
        "task_id": st.get("task_id"),
        "name": st.get("name"),
        "attempt": st.get("attempt", 0),
        "kind": st.get("kind"),
        # Tracing linkage: a stalled TRACED task's report names its trace,
        # so `ray-tpu stalls` links straight to `ray-tpu timeline --trace`.
        "trace_id": st.get("trace_id"),
        "worker_id": worker_id,
        "node_id": node_id,
        "pid": pid,
        "silence_s": round(float(silence_s), 3),
        "running_s": round(time.monotonic() - st.get("started", 0.0), 3),
        "time": time.time(),
        "reason": reason or f"no progress for {silence_s:.1f}s",
        "events": flight_events(),
        # CONFIG resolves _system_config overrides first, then the
        # RT_STALL_FLIGHT_DIR env (train runs inject it per worker).
        "flight_dir": (CONFIG.stall_flight_dir
                       or default_flight_dir(session_id)),
    }


class Watchdog:
    """Per-worker monitor thread driving the escalation ladder.

    `on_report(report)` runs on the monitor thread for each stage crossing;
    `on_beacon(task_id_or_None, silence_s)` runs every tick so the node
    agent can detect a worker whose monitor thread itself got starved (user
    code holding the GIL in native code) — beacons stopping IS the signal
    the agent-side backstop escalates on."""

    def __init__(self, *, worker_id: str, node_id: str, session_id: str,
                 on_report: Callable[[dict], None],
                 on_beacon: Callable[[Optional[str], float], None] | None = None):
        self.worker_id = worker_id
        self.node_id = node_id
        self.session_id = session_id
        self.on_report = on_report
        self.on_beacon = on_beacon
        self._pid = os.getpid()
        # (task_id, attempt) -> set of stages already emitted.
        self._emitted: dict[tuple, set] = {}
        # (task_id, attempt) -> trace id minted by the always-sample
        # escalation for UNSAMPLED stalled tasks (tracing.escalation_root);
        # later stages of the same attempt reuse it.
        self._esc_traces: dict[tuple, str] = {}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def start(self) -> bool:
        global _armed
        if not enabled():
            return False  # escalation disabled: no thread, no beacons
        _armed = True
        if _ensure_ring() is not None:
            # RPC frame events feed the ring only while the stall plane is
            # armed (the hook costs one global check per frame otherwise).
            from ray_tpu._private import rpc as _rpc

            _rpc.set_flight_hook(record)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="rt-watchdog")
        self._thread.start()
        return True

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        ladder = sorted(stages().items(), key=lambda kv: kv[1])
        interval = max(0.05, float(CONFIG.stall_beacon_interval_s))
        while not self._stop.wait(interval):
            try:
                self._tick(ladder)
            except Exception:
                pass  # the watchdog must never take the worker down

    def _tick(self, ladder: list) -> None:
        now = time.monotonic()
        states = executing_snapshot()
        live_keys = set()
        worst_silence = 0.0
        beacon_task = None
        for st in states:
            key = (st["task_id"], st["attempt"])
            live_keys.add(key)
            silence = now - st["last_progress"]
            if silence > worst_silence:
                worst_silence = silence
                beacon_task = st["task_id"]
            emitted = self._emitted.setdefault(key, set())
            for stage, threshold in ladder:
                if silence >= threshold and stage not in emitted:
                    # Mark emitted only AFTER a successful hand-off: a
                    # report lost to a reconnecting agent connection (or a
                    # transient build failure) retries next tick instead of
                    # being swallowed forever — a permanently-swallowed
                    # kill stage would recreate the very hang this plane
                    # exists to prevent.
                    delivered = False
                    try:
                        rep = build_report(
                            st, stage, worker_id=self.worker_id,
                            node_id=self.node_id, pid=self._pid,
                            session_id=self.session_id, silence_s=silence)
                        if rep.get("trace_id") is None:
                            # Always-sample escalation: an UNSAMPLED (or
                            # untraced-root) stalled task still gets a
                            # trace root so the report links to a
                            # timeline. No-op with tracing off.
                            rep["trace_id"] = self._stall_trace(key, st)
                        delivered = self.on_report(rep) is not False
                    except Exception:
                        delivered = False
                    if delivered:
                        emitted.add(stage)
                        record("stall_" + stage,
                               f"{st['name']} silent {silence:.1f}s")
        # Prune ladder bookkeeping of finished attempts.
        for key in [k for k in self._emitted if k not in live_keys]:
            self._emitted.pop(key, None)
        for key in [k for k in self._esc_traces if k not in live_keys]:
            self._esc_traces.pop(key, None)
        if self.on_beacon is not None:
            try:
                self.on_beacon(beacon_task, worst_silence)
            except Exception:
                pass

    def _stall_trace(self, key: tuple, st: dict):
        """Mint (once per attempt) an escalation trace root for a stalled
        task that carries no sampled trace context."""
        tid = self._esc_traces.get(key)
        if tid is None:
            from ray_tpu._private import tracing

            tid = tracing.escalation_root(st)
            if tid is not None:
                self._esc_traces[key] = tid
        return tid
