"""In-process head bootstrap: controller + head node agent.

Parity target: reference python/ray/_private/node.py
(start_head_processes:1437 — spawns the gcs_server and raylet C++ binaries as
daemons). TPU-era simplification: the control plane is asyncio services, so a
single-host cluster hosts controller + head agent on the driver's IO loop
thread — zero extra processes beyond the worker pool; `ray-tpu start` runs
the same objects standalone for multi-host clusters.
"""

from __future__ import annotations

import os
import uuid

from ray_tpu._private import rpc
from ray_tpu._private.accelerators import host_resources
from ray_tpu._private.controller import Controller
from ray_tpu._private.ids import NodeID
from ray_tpu._private.node_agent import NodeAgent
from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rtconfig import CONFIG


class HeadNode:
    """Controller + head NodeAgent living on one event loop thread."""

    def __init__(
        self,
        num_cpus: float | None = None,
        num_tpus: float | None = None,
        resources: dict | None = None,
        labels: dict | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        worker_env: dict | None = None,
        session_id: str | None = None,
    ):
        # An explicit session_id restarts a head INTO an existing session
        # (controller-restart FT: surviving agents/workers keep their shm
        # namespace and re-register).
        self.session_id = session_id or uuid.uuid4().hex
        self.host = host
        self.port = port
        res = host_resources(num_cpus, num_tpus)
        res.update(resources or {})
        self.resources = ResourceSet(res)
        self.labels = labels or {}
        self.worker_env = dict(worker_env or {})
        # Workers must be able to unpickle by-reference functions from any
        # module the DRIVER can import (e.g. pytest-inserted test dirs, user
        # script dirs). For a local head, inheriting the driver's sys.path
        # is the runtime-env equivalent of the reference's working_dir
        # shipping (python/ray/_private/runtime_env/packaging.py).
        import sys

        # Keep zipimport entries (.egg/.zip) too; explicit user-provided
        # PYTHONPATH stays FIRST so it can shadow inherited driver paths.
        driver_paths = [p for p in sys.path if p and os.path.exists(p)]
        existing = self.worker_env.get("PYTHONPATH", "")
        self.worker_env["PYTHONPATH"] = os.pathsep.join(
            ([existing] if existing else []) + driver_paths)
        self.io = rpc.EventLoopThread(name="rt-head")
        self.controller: Controller | None = None
        self.agent: NodeAgent | None = None
        self.node_id = NodeID.from_random().hex()
        self.controller_addr: tuple | None = None

    def start(self) -> tuple:
        async def _up():
            self.controller = Controller(self.session_id)
            port = await self.controller.start(self.host, self.port)
            self.controller_addr = (self.host, port)
            self.agent = NodeAgent(
                node_id=self.node_id,
                session_id=self.session_id,
                controller_addr=self.controller_addr,
                resources_raw=self.resources.raw(),
                labels=self.labels,
                host=self.host,
                env=self.worker_env,
            )
            await self.agent.start()

        self.io.run(_up(), timeout=CONFIG.connect_timeout_s)
        return self.controller_addr

    def stop(self):
        async def _down():
            if self.agent is not None:
                await self.agent.stop()
            if self.controller is not None:
                await self.controller.stop()

        try:
            self.io.run(_down(), timeout=10)
        except Exception:
            pass
        self.io.stop()
        # Clean any session shm leftovers.
        import glob

        for p in glob.glob(os.path.join(CONFIG.shm_dir, f"rt_{self.session_id[:8]}_*")):
            try:
                os.unlink(p)
            except OSError:
                pass
