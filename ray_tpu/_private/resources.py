"""Fixed-point resource arithmetic and resource sets.

Parity target: reference src/ray/common/scheduling/fixed_point.h (1e-4 units)
and resource_set.h / cluster_resource_data.h. TPU chips are first-class here:
the scheduler treats "TPU" like the reference treats "GPU", plus pod-level
custom resources like "TPU-v5e-8-head" (cf. reference
python/ray/_private/accelerators/tpu.py:109).
"""

from __future__ import annotations

from ray_tpu._private.rtconfig import CONFIG


def _unit() -> int:
    return CONFIG.resource_unit


class ResourceSet:
    """Mapping resource name -> fixed-point quantity (ints, 1/10000 units)."""

    __slots__ = ("_r",)

    def __init__(self, mapping: dict[str, float] | None = None, _raw: dict[str, int] | None = None):
        if _raw is not None:
            self._r = {k: v for k, v in _raw.items() if v != 0}
        else:
            u = _unit()
            self._r = {}
            for k, v in (mapping or {}).items():
                q = round(float(v) * u)
                if q != 0:
                    self._r[k] = q

    def to_dict(self) -> dict[str, float]:
        u = _unit()
        return {k: v / u for k, v in self._r.items()}

    def raw(self) -> dict[str, int]:
        return dict(self._r)

    def get(self, name: str) -> float:
        return self._r.get(name, 0) / _unit()

    def is_empty(self) -> bool:
        return not self._r

    def fits(self, other: "ResourceSet") -> bool:
        """True if `other` (a demand) fits within self (availability)."""
        return all(self._r.get(k, 0) >= v for k, v in other._r.items())

    def subtract(self, other: "ResourceSet") -> None:
        for k, v in other._r.items():
            self._r[k] = self._r.get(k, 0) - v
            if self._r[k] == 0:
                del self._r[k]

    def add(self, other: "ResourceSet") -> None:
        for k, v in other._r.items():
            self._r[k] = self._r.get(k, 0) + v
            if self._r[k] == 0:
                del self._r[k]

    def copy(self) -> "ResourceSet":
        return ResourceSet(_raw=dict(self._r))

    def __bool__(self):
        return bool(self._r)

    def __eq__(self, other):
        return isinstance(other, ResourceSet) and self._r == other._r

    def __repr__(self):
        return f"ResourceSet({self.to_dict()})"

    def __reduce__(self):
        return (ResourceSet, (None, dict(self._r)))


def normalize_resources(
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    memory: float | None = None,
    default_cpus: float = 1.0,
) -> ResourceSet:
    """Build a task/actor resource demand (cf. reference remote_function.py
    options resolution — default 1 CPU for tasks, 0 for actors)."""
    r = dict(resources or {})
    r["CPU"] = float(num_cpus) if num_cpus is not None else default_cpus
    if num_tpus is not None:
        r["TPU"] = float(num_tpus)
    if memory is not None:
        r["memory"] = float(memory)
    return ResourceSet({k: v for k, v in r.items() if v})
