"""In-process memory backend (`mem://`).

A flat key->bytes dict behind a lock: the fastest way to unit-test engine
semantics (manifest commit, retention, resharding restore) with zero
filesystem traffic. Process-local by design — actors cannot share a
mem:// root; use local:// or sim:// for cross-process tests.
"""

from __future__ import annotations

import threading

from ray_tpu.storage.backend import StorageBackend, StorageNotFoundError


class MemBackend(StorageBackend):
    scheme = "mem"

    # Class-level so every get_backend("mem://...") sees one namespace in
    # this process (mirrors how a bucket outlives client objects).
    _store: dict[str, bytes] = {}
    _lock = threading.Lock()

    @staticmethod
    def _norm(path: str) -> str:
        return path.strip("/")

    def put(self, path: str, data) -> int:
        if isinstance(data, (bytes, bytearray, memoryview)):
            blob = bytes(data)
        else:
            blob = b"".join(bytes(p) for p in data)
        with self._lock:
            self._store[self._norm(path)] = blob
        return len(blob)

    def get(self, path: str) -> bytes:
        with self._lock:
            try:
                return self._store[self._norm(path)]
            except KeyError as e:
                raise StorageNotFoundError(path) from e

    def exists(self, path: str) -> bool:
        p = self._norm(path)
        with self._lock:
            if p in self._store:
                return True
            prefix = p + "/"
            return any(k.startswith(prefix) for k in self._store)

    def listdir(self, path: str) -> list[str]:
        p = self._norm(path)
        prefix = p + "/" if p else ""
        out = set()
        with self._lock:
            for k in self._store:
                if k.startswith(prefix):
                    out.add(k[len(prefix):].split("/", 1)[0])
        return sorted(out)

    def delete(self, path: str) -> bool:
        with self._lock:
            return self._store.pop(self._norm(path), None) is not None

    def delete_prefix(self, path: str) -> None:
        p = self._norm(path)
        prefix = p + "/"
        with self._lock:
            for k in [k for k in self._store
                      if k == p or k.startswith(prefix)]:
                del self._store[k]

    def rename(self, src: str, dst: str) -> None:
        s, d = self._norm(src), self._norm(dst)
        sp, dp = s + "/", d + "/"
        with self._lock:
            if s in self._store:
                self._store[d] = self._store.pop(s)
                return
            moved = False
            for k in [k for k in self._store if k.startswith(sp)]:
                self._store[dp + k[len(sp):]] = self._store.pop(k)
                moved = True
            if not moved:
                raise StorageNotFoundError(src)

    def size(self, path: str) -> int:
        with self._lock:
            try:
                return len(self._store[self._norm(path)])
            except KeyError as e:
                raise StorageNotFoundError(path) from e

    def makedirs(self, path: str) -> None:
        pass  # flat keyspace

    @classmethod
    def clear_all(cls) -> None:
        """Test hook: wipe the namespace."""
        with cls._lock:
            cls._store.clear()
