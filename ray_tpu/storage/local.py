"""Local-filesystem storage backend (`local://` and bare paths).

The default backend for every durable consumer: controller snapshots,
train/tune checkpoints, workflow step memoization. Puts are atomic
(tmp file + os.replace), so a reader — including another process on the
same host — never sees a torn object; rename maps to os.replace, the same
primitive the pre-storage-plane code used for its commit points.
"""

from __future__ import annotations

import os
import shutil
import tempfile

from ray_tpu.storage.backend import (
    StorageBackend,
    StorageError,
    StorageNotFoundError,
)


class LocalBackend(StorageBackend):
    scheme = "local"

    def put(self, path: str, data) -> int:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(prefix=".rtput_", dir=d or ".")
        n = 0
        try:
            with os.fdopen(fd, "wb") as f:
                if isinstance(data, (bytes, bytearray, memoryview)):
                    f.write(data)
                    n = len(data)
                else:
                    for part in data:
                        f.write(part)
                        n += len(part)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return n

    def get(self, path: str) -> bytes:
        try:
            with open(path, "rb") as f:
                return f.read()
        except FileNotFoundError as e:
            raise StorageNotFoundError(path) from e

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        try:
            return sorted(os.listdir(path))
        except (FileNotFoundError, NotADirectoryError):
            return []

    def delete(self, path: str) -> bool:
        try:
            os.unlink(path)
            return True
        except FileNotFoundError:
            return False
        except IsADirectoryError:
            shutil.rmtree(path, ignore_errors=True)
            return True

    def delete_prefix(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        else:
            try:
                os.unlink(path)
            except OSError:
                pass

    def rename(self, src: str, dst: str) -> None:
        d = os.path.dirname(dst)
        if d:
            os.makedirs(d, exist_ok=True)
        try:
            os.replace(src, dst)
        except OSError as e:
            # Directory with a non-empty destination: fall back to move.
            if os.path.isdir(src):
                shutil.move(src, dst)
            else:
                raise StorageError(f"rename {src} -> {dst}: {e}") from e

    def size(self, path: str) -> int:
        try:
            return os.path.getsize(path)
        except OSError as e:
            raise StorageNotFoundError(path) from e

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def isdir(self, path: str) -> bool:
        return os.path.isdir(path)
