"""Storage backend seam: the ONE pluggable boundary every durable byte in
the runtime crosses.

Parity target: the role pyarrow.fs plays for reference ray.train/tune
storage (storage_context.py) and the GCS store client plays for controller
state (redis_store_client.h) — except here there is a single ABC shared by
controller snapshots, train/tune checkpoints, and workflow step memoization,
so a new scheme (GCS, S3, ...) plugs in once and every consumer gets it.

A backend is addressed by URI scheme:

    local:///abs/path   (also any bare path)  — the host filesystem
    mem://bucket/key                          — in-process dict (tests)
    sim:///abs/path                           — fault-injectable "remote"
                                                backend over the local fs
                                                (latency/bandwidth caps,
                                                injected failures; see
                                                storage/sim.py)

Semantics every backend must honor:
  - `put` is atomic: a reader never observes a partially written object
    (local: tmp file + os.replace; mem: dict assignment under lock).
  - `rename` is atomic within the backend — the commit primitive the
    checkpoint engine's manifest-last protocol builds on.
  - `listdir` is one level (like os.listdir), returning names.
Paths use "/" separators regardless of backend.
"""

from __future__ import annotations

import os
import re
import threading
from abc import ABC, abstractmethod
from typing import Callable, Iterable, Union

Parts = Union[bytes, bytearray, memoryview, Iterable]


class StorageError(Exception):
    """Base class for storage-plane failures."""


class StorageTransientError(StorageError):
    """Retryable failure (network blip, injected sim:// fault): callers on
    durable paths (the checkpoint writer) retry these with backoff."""


class StorageNotFoundError(StorageError, FileNotFoundError):
    """The addressed object does not exist."""


class StorageBackend(ABC):
    """Streaming put/get/list/delete/rename over scheme-local paths."""

    scheme: str = ""

    @abstractmethod
    def put(self, path: str, data: Parts) -> int:
        """Atomically store `data` (bytes or an iterable of bytes-like
        parts, written in order — the pickle5-oob streaming shape) at
        `path`, creating parents. Returns bytes written."""

    @abstractmethod
    def get(self, path: str) -> bytes:
        """Full contents of `path`; StorageNotFoundError if absent."""

    @abstractmethod
    def exists(self, path: str) -> bool: ...

    @abstractmethod
    def listdir(self, path: str) -> list[str]:
        """Immediate child names of `path` (empty when absent)."""

    @abstractmethod
    def delete(self, path: str) -> bool:
        """Remove one object; True if it existed."""

    @abstractmethod
    def delete_prefix(self, path: str) -> None:
        """Remove `path` and everything under it (recursive, best-effort)."""

    @abstractmethod
    def rename(self, src: str, dst: str) -> None:
        """Atomic move within this backend (the manifest commit point)."""

    @abstractmethod
    def size(self, path: str) -> int: ...

    def makedirs(self, path: str) -> None:
        """Ensure a directory exists (no-op on flat keyspaces)."""

    def isdir(self, path: str) -> bool:
        return bool(self.listdir(path))


# ------------------------------------------------------------- registry
_SCHEME_RE = re.compile(r"^([a-z][a-z0-9+.-]*)://")
_REGISTRY: dict[str, Callable[[], StorageBackend]] = {}
_INSTANCES: dict[str, StorageBackend] = {}
_reg_lock = threading.Lock()


def register_backend(scheme: str, factory: Callable[[], StorageBackend]) -> None:
    """Plug a new scheme in (factory is called once, lazily)."""
    with _reg_lock:
        _REGISTRY[scheme] = factory
        _INSTANCES.pop(scheme, None)


def parse_uri(uri: str) -> tuple[str, str]:
    """Split a URI into (scheme, backend-local path). Bare paths (no
    scheme) are `local`. `local:///a/b` and `sim:///a/b` keep the absolute
    fs path; `mem://bucket/k` keeps `bucket/k`."""
    m = _SCHEME_RE.match(uri)
    if not m:
        return "local", uri
    scheme = m.group(1)
    rest = uri[m.end():]
    if scheme == "file":
        scheme = "local"
    if scheme in ("local", "sim"):
        # local:///abs -> /abs (the third slash is the path root)
        if not rest.startswith("/"):
            rest = "/" + rest
        return scheme, rest
    return scheme, rest


def get_backend(uri: str) -> tuple[StorageBackend, str]:
    """Resolve `uri` to (backend instance, backend-local path)."""
    scheme, path = parse_uri(uri)
    with _reg_lock:
        be = _INSTANCES.get(scheme)
        if be is None:
            factory = _REGISTRY.get(scheme)
            if factory is None:
                raise StorageError(
                    f"no storage backend registered for scheme {scheme!r} "
                    f"(known: {sorted(_REGISTRY)})")
            be = _INSTANCES[scheme] = factory()
    return be, path


def scheme_of(uri: str) -> str:
    return parse_uri(uri)[0]


def is_local(uri: str) -> bool:
    """True when `uri` addresses the plain host filesystem — consumers may
    then hand the path to code that open()s it directly. sim:// is
    fs-backed but NOT local: direct access would bypass fault injection."""
    return scheme_of(uri) == "local"


def local_path(uri: str) -> str | None:
    """Filesystem path for a local URI, else None."""
    scheme, path = parse_uri(uri)
    return path if scheme == "local" else None


def join(uri: str, *parts: str) -> str:
    """URI-aware path join; keeps bare paths bare (so the default local
    flow produces ordinary fs paths)."""
    out = uri
    for p in parts:
        if not p:
            continue
        out = out.rstrip("/") + "/" + str(p).lstrip("/")
    return out


def basename(uri: str) -> str:
    return uri.rstrip("/").rsplit("/", 1)[-1]


def parent(uri: str) -> str:
    head = uri.rstrip("/").rsplit("/", 1)[0]
    return head if head else "/"


# ------------------------------------------------- module-level conveniences
# The write/read/rename conveniences every consumer rides (controller
# snapshots, train checkpoints, tune state, workflow memoization, flight
# dumps) carry tracing spans: inside a traced context a storage op becomes
# a `storage.*` span with scheme + byte count, so checkpoint stalls and
# slow backends show up in the request/step timeline. Zero-cost when
# tracing is off or the context unsampled (see _private/tracing.span).
from ray_tpu._private import tracing as _tracing  # noqa: E402


def put(uri: str, data: Parts) -> int:
    be, p = get_backend(uri)
    with _tracing.span("storage.put", "storage", {"scheme": be.scheme or
                                                  scheme_of(uri)}):
        return be.put(p, data)


def get_bytes(uri: str) -> bytes:
    be, p = get_backend(uri)
    with _tracing.span("storage.get", "storage", {"scheme": be.scheme or
                                                  scheme_of(uri)}):
        return be.get(p)


def exists(uri: str) -> bool:
    be, p = get_backend(uri)
    return be.exists(p)


def listdir(uri: str) -> list[str]:
    be, p = get_backend(uri)
    return be.listdir(p)


def delete(uri: str) -> bool:
    be, p = get_backend(uri)
    return be.delete(p)


def delete_prefix(uri: str) -> None:
    be, p = get_backend(uri)
    be.delete_prefix(p)


def rename(src_uri: str, dst_uri: str) -> None:
    be, src = get_backend(src_uri)
    be2, dst = get_backend(dst_uri)
    if be is not be2:
        raise StorageError("rename must stay within one backend "
                           f"({src_uri} -> {dst_uri})")
    with _tracing.span("storage.rename", "storage",
                       {"scheme": be.scheme or scheme_of(src_uri)}):
        be.rename(src, dst)


def makedirs(uri: str) -> None:
    be, p = get_backend(uri)
    be.makedirs(p)


def size(uri: str) -> int:
    be, p = get_backend(uri)
    return be.size(p)


def _register_builtins() -> None:
    from ray_tpu.storage.local import LocalBackend
    from ray_tpu.storage.mem import MemBackend
    from ray_tpu.storage.sim import SimBackend

    register_backend("local", LocalBackend)
    register_backend("mem", MemBackend)
    register_backend("sim", SimBackend)


_register_builtins()


def _normpath(path: str) -> str:
    return os.path.normpath(path)
