"""ray_tpu.storage — the pluggable persistent-storage plane.

One `StorageBackend` seam (README "Checkpointing & storage") shared by
every durable consumer in the runtime: controller state snapshots,
train/tune checkpoints (via the async sharded engine in
`ray_tpu/train/checkpoint.py`), and workflow step memoization. Backends
are addressed by URI scheme — `local://` (and bare paths), `mem://`, and
the fault-injectable `sim://` — and new schemes plug in with
`register_backend`.
"""

from ray_tpu.storage.backend import (  # noqa: F401
    StorageBackend,
    StorageError,
    StorageNotFoundError,
    StorageTransientError,
    basename,
    delete,
    delete_prefix,
    exists,
    get_backend,
    get_bytes,
    is_local,
    join,
    listdir,
    local_path,
    makedirs,
    parent,
    parse_uri,
    put,
    register_backend,
    rename,
    scheme_of,
    size,
)
from ray_tpu.storage import sim  # noqa: F401

__all__ = [
    "StorageBackend",
    "StorageError",
    "StorageNotFoundError",
    "StorageTransientError",
    "register_backend",
    "get_backend",
    "parse_uri",
    "scheme_of",
    "is_local",
    "local_path",
    "join",
    "basename",
    "parent",
    "put",
    "get_bytes",
    "exists",
    "listdir",
    "delete",
    "delete_prefix",
    "rename",
    "makedirs",
    "size",
    "sim",
]
