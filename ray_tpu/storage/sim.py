"""Simulated remote storage backend (`sim://`).

The chaos surface for the storage plane, reusing the FaultInjector idiom
from `_private/rpc.py`: a deterministic rule table (op filter + after/times
schedule) that injects failures, plus latency and bandwidth caps so saves
take long enough to kill things in the middle of. Data lands on the local
filesystem underneath (so a process killed mid-save leaves real partial
files for GC tests to find), but consumers must treat sim:// as remote —
`storage.is_local` is False, and direct fs access bypasses injection.

Knobs (env / `_system_config`, read per-op so tests and subprocesses can
flip them without rebuilding backends):
    RT_SIM_STORAGE_LATENCY_S  per-operation latency
    RT_SIM_STORAGE_GBPS       put/get bandwidth cap (0 = unlimited)
    RT_SIM_STORAGE_SEVERED    every op raises StorageTransientError

In-process rules (same shape as rpc.FaultInjector.add_rule):

    faults().add_rule(op="put", after=2, times=1)       # 3rd put fails
    faults().add_rule(op="put", error="fatal")          # non-retryable
    faults().sever()                                    # all ops fail
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from ray_tpu.storage.backend import (
    StorageBackend,
    StorageError,
    StorageTransientError,
)
from ray_tpu.storage.local import LocalBackend


@dataclass
class SimFaultRule:
    op: str = "*"              # put|get|list|delete|rename|size|*
    error: str = "transient"   # transient|fatal
    after: int = 0             # matching ops to let through first
    times: Optional[int] = None  # fire at most N times (None = forever)
    match: Optional[Callable[[str], bool]] = None  # path filter
    _seen: int = 0
    _fired: int = 0
    _lock: threading.Lock = field(default_factory=threading.Lock)

    def admit(self, op: str, path: str) -> bool:
        if self.op != "*" and self.op != op:
            return False
        if self.match is not None and not self.match(path):
            return False
        with self._lock:
            self._seen += 1
            if self._seen <= self.after:
                return False
            if self.times is not None and self._fired >= self.times:
                return False
            self._fired += 1
            return True


class SimFaults:
    """Rule registry + counters (the rpc.FaultInjector idiom, storage
    edition). `stats` counts injected failures per op so tests can assert
    the schedule fired — and that retries actually happened."""

    def __init__(self):
        self._rules: list[SimFaultRule] = []
        self._lock = threading.Lock()
        self.severed = False
        self.stats: dict[str, int] = {}

    def add_rule(self, op: str = "*", *, error: str = "transient",
                 after: int = 0, times: Optional[int] = None,
                 match=None) -> SimFaultRule:
        rule = SimFaultRule(op=op, error=error, after=after, times=times,
                            match=match)
        with self._lock:
            self._rules.append(rule)
        return rule

    def remove_rule(self, rule: SimFaultRule) -> None:
        with self._lock:
            if rule in self._rules:
                self._rules.remove(rule)

    def sever(self) -> None:
        """Simulated network partition to the storage service: every op
        fails transiently until restore()."""
        self.severed = True

    def restore(self) -> None:
        self.severed = False

    def clear(self) -> None:
        with self._lock:
            self._rules.clear()
            self.stats.clear()
        self.severed = False

    def check(self, op: str, path: str) -> None:
        from ray_tpu._private.rtconfig import CONFIG

        if self.severed or CONFIG.sim_storage_severed:
            with self._lock:
                self.stats["severed"] = self.stats.get("severed", 0) + 1
            raise StorageTransientError(
                f"sim storage severed ({op} {path})")
        with self._lock:
            rules = list(self._rules)
        for rule in rules:
            if rule.admit(op, path):
                with self._lock:
                    self.stats[op] = self.stats.get(op, 0) + 1
                if rule.error == "fatal":
                    raise StorageError(
                        f"sim storage injected fatal {op} failure ({path})")
                raise StorageTransientError(
                    f"sim storage injected transient {op} failure ({path})")


_FAULTS = SimFaults()


def faults() -> SimFaults:
    return _FAULTS


class SimBackend(StorageBackend):
    scheme = "sim"

    def __init__(self):
        self._fs = LocalBackend()

    # -- shaping -----------------------------------------------------------
    def _pre(self, op: str, path: str, nbytes: int = 0) -> None:
        from ray_tpu._private.rtconfig import CONFIG

        _FAULTS.check(op, path)
        lat = CONFIG.sim_storage_latency_s
        if lat > 0:
            time.sleep(lat)
        gbps = CONFIG.sim_storage_gbps
        if gbps > 0 and nbytes:
            time.sleep(min(nbytes / (gbps * 1e9), 30.0))

    # -- ops ---------------------------------------------------------------
    def put(self, path: str, data) -> int:
        if not isinstance(data, (bytes, bytearray, memoryview)):
            data = b"".join(bytes(p) for p in data)
        self._pre("put", path, len(data))
        return self._fs.put(path, data)

    def get(self, path: str) -> bytes:
        # Size known only after the read; charge bandwidth on the result.
        self._pre("get", path)
        out = self._fs.get(path)
        from ray_tpu._private.rtconfig import CONFIG

        gbps = CONFIG.sim_storage_gbps
        if gbps > 0 and out:
            time.sleep(min(len(out) / (gbps * 1e9), 30.0))
        return out

    def exists(self, path: str) -> bool:
        self._pre("list", path)
        return self._fs.exists(path)

    def listdir(self, path: str) -> list[str]:
        self._pre("list", path)
        return self._fs.listdir(path)

    def delete(self, path: str) -> bool:
        self._pre("delete", path)
        return self._fs.delete(path)

    def delete_prefix(self, path: str) -> None:
        self._pre("delete", path)
        self._fs.delete_prefix(path)

    def rename(self, src: str, dst: str) -> None:
        self._pre("rename", src)
        self._fs.rename(src, dst)

    def size(self, path: str) -> int:
        self._pre("size", path)
        return self._fs.size(path)

    def makedirs(self, path: str) -> None:
        self._fs.makedirs(path)

    def isdir(self, path: str) -> bool:
        return self._fs.isdir(path)
