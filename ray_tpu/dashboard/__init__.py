"""Dashboard: HTTP/JSON view of cluster state.

Parity target: reference python/ray/dashboard/head.py:46 (DashboardHead —
an aiohttp server aggregating GCS state for the web UI) with the module
endpoints that matter operationally (dashboard/modules/{node,actor,job,
state,reporter}): nodes, actors, tasks, objects, jobs, cluster status, and
a chrome-trace timeline. JSON only — point curl/a browser at it; the
reference's React frontend is intentionally out of scope.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from ray_tpu._private import rpc

logger = logging.getLogger(__name__)

# Single-file live UI (the miniature of the reference's React dashboard
# client): vanilla JS polling the JSON APIs below, no build step, no deps.
_INDEX_HTML = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>
 body{font-family:ui-monospace,Menlo,monospace;margin:1.2rem;background:#101418;color:#d8dee6}
 h1{font-size:1.1rem} h2{font-size:.95rem;margin:1.2rem 0 .4rem;color:#8ab4f8}
 table{border-collapse:collapse;width:100%;font-size:.8rem}
 th,td{text-align:left;padding:.25rem .6rem;border-bottom:1px solid #2a3138}
 th{color:#9aa6b2;font-weight:600} .ok{color:#7ee787} .bad{color:#ff7b72}
 #meta{color:#9aa6b2;font-size:.8rem} a{color:#8ab4f8}
 .pill{display:inline-block;padding:0 .45rem;border-radius:.6rem;background:#1d2630;margin-right:.6rem}
 .spark{display:inline-block;margin:0 1rem .3rem 0}
 .spark svg{vertical-align:middle;background:#161c22;border-radius:3px}
 .spark .lbl{color:#9aa6b2;font-size:.75rem;margin-right:.3rem}
 .spark .val{color:#7ee787;font-size:.75rem;margin-left:.3rem}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div id="meta"></div>
<div id="res"></div>
<div id="util"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Recent events</h2><table id="events"></table>
<h2>Recent tasks</h2><table id="tasks"></table>
<p><a href="/api/timeline">timeline</a> (chrome trace; load in Perfetto) &middot;
<a href="/api/traces">traces</a> (causal spans; RT_TRACING=1) &middot;
<a href="/api/events">events</a> (lifecycle history; ray-tpu events) &middot;
<a href="/api/timeseries">timeseries</a> (RT_TELEMETRY_INTERVAL_S) &middot;
<a href="/api/profiles">profiles</a> (ray-tpu profile) &middot;
<a href="/metrics">prometheus /metrics</a></p>
<script>
const esc=(v)=>String(v).replace(/&/g,"&amp;").replace(/</g,"&lt;")
  .replace(/>/g,"&gt;").replace(/"/g,"&quot;");
const fmt=(o)=>esc(typeof o==="object"?JSON.stringify(o):o);
function table(el,rows,cols){
  let h="<tr>"+cols.map(c=>"<th>"+c+"</th>").join("")+"</tr>";
  for(const r of rows) h+="<tr>"+cols.map(c=>{
    let v=fmt(r[c]??"");
    if(c==="alive"||c==="status"||c==="state"){
      const good=(v===true||v==="true"||v==="ALIVE"||v==="RUNNING"||v==="SUCCEEDED");
      v="<span class='"+(good?"ok":"bad")+"'>"+v+"</span>";}
    return "<td>"+v+"</td>";}).join("")+"</tr>";
  document.getElementById(el).innerHTML=h;
}
async function j(u){const r=await fetch(u);return r.json()}
function spark(pts,w,h){ // inline SVG polyline over [[ts,v],...]
  if(!pts.length) return "";
  const t0=pts[0][0],t1=pts[pts.length-1][0]||t0+1;
  let hi=Math.max(...pts.map(p=>p[1]),1e-9),lo=Math.min(...pts.map(p=>p[1]),0);
  if(hi===lo) hi=lo+1;
  const xy=pts.map(p=>((p[0]-t0)/Math.max(1e-9,t1-t0)*(w-2)+1).toFixed(1)+","+
    ((h-1)-(p[1]-lo)/(hi-lo)*(h-2)).toFixed(1)).join(" ");
  return "<svg width='"+w+"' height='"+h+"'><polyline fill='none' "+
    "stroke='#8ab4f8' stroke-width='1' points='"+xy+"'/></svg>";
}
async function util(){ // live sparkline row (RT_TELEMETRY_INTERVAL_S armed)
  try{
    // no since= (browser clocks skew vs the controller host); prefix
    // filters keep per-worker series out of the 2s poll entirely, and we
    // window the tail client-side against the server's own clock.
    const [tn,tc]=await Promise.all([
      j("/api/timeseries?series=node."),
      j("/api/timeseries?series=ctrl.loop_lag_s")]);
    const ts={now:tn.now,series:(tn.series||[]).concat(tc.series||[])};
    const rows=ts.series.filter(r=>!r.worker_id&&
      ["node.cpu","node.mem","node.rss","node.tasks_running",
       "ctrl.loop_lag_s"].includes(r.series));
    let h="";
    for(const r of rows){
      const pts=r.points.filter(p=>p[0]>ts.now-120).slice(-120);
      if(!pts.length) continue;
      const last=pts[pts.length-1][1];
      h+="<span class='spark'><span class='lbl'>"+esc(r.node_id.slice(0,8))+
        " "+esc(r.series)+"</span>"+spark(pts,120,24)+
        "<span class='val'>"+esc(typeof last==="number"?
        (last>=1e6?(last/1048576).toFixed(0)+"M":last):last)+"</span></span>";
    }
    document.getElementById("util").innerHTML=h;
  }catch(e){}
}
async function tick(){
  util();
  try{
    const [st,nodes,actors,jobs,tasks,events]=await Promise.all([
      j("/api/cluster_status"),j("/api/nodes"),j("/api/actors"),
      j("/api/jobs"),j("/api/tasks?limit=25"),j("/api/events?limit=15")]);
    document.getElementById("meta").textContent=
      "updated "+new Date().toLocaleTimeString();
    const tot=st.total||{},av=st.available||{};
    document.getElementById("res").innerHTML=Object.keys(tot).map(k=>
      "<span class='pill'>"+k+" "+(av[k]??0)+"/"+tot[k]+"</span>").join("");
    table("nodes",nodes.nodes||[],["node_id","alive","address","total","available"]);
    table("actors",actors.actors||[],["actor_id","class","state","name","node_id","restarts_used"]);
    table("jobs",jobs.jobs||[],["submission_id","status","entrypoint","message"]);
    const erows=(events.events||[]).slice(-15).reverse().map(e=>({...e,
      time:new Date((e.ts||0)*1000).toLocaleTimeString(),
      entity:(e.entity||[]).map(x=>String(x).slice(0,12)).join(",")}));
    table("events",erows,["seq","time","sev","kind","entity","msg"]);
    const trows=(tasks.tasks||[]).slice(-25).reverse().map(t=>({...t,
      duration_ms:(t.end&&t.start)?Math.round((t.end-t.start)*1000):""}));
    table("tasks",trows,["name","kind","state","duration_ms","node_id"]);
  }catch(e){document.getElementById("meta").textContent="refresh failed: "+e}
}
tick();setInterval(tick,2000);
</script></body></html>"""


def render_prometheus(metrics: list[dict]) -> str:
    """Prometheus text exposition from aggregated metric entries.

    Grouped per family FIRST so `# HELP`/`# TYPE` are emitted exactly once
    per metric name even when series with different tag sets interleave in
    the input (and HELP comes from whichever series carries a description,
    not just the first seen). Histogram cumulative buckets: the `+Inf`
    bucket equals `_count` by construction — the finite loop consumes
    buckets[:-1] and the overflow bucket buckets[-1] is added exactly once
    (pinned against empty AND non-empty overflow buckets in
    tests/test_telemetry.py)."""

    def esc(v) -> str:
        # Prometheus label-value escaping: backslash, quote, newline.
        return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                .replace("\n", "\\n"))

    families: dict[str, dict] = {}
    for m in metrics:
        name = m["name"].replace(".", "_").replace("-", "_")
        fam = families.setdefault(name, {"kind": m["kind"], "desc": "",
                                         "series": []})
        if m.get("desc") and not fam["desc"]:
            fam["desc"] = m["desc"]
        fam["series"].append(m)
    lines: list[str] = []
    for name, fam in families.items():
        kind = {"counter": "counter", "gauge": "gauge",
                "histogram": "histogram"}.get(fam["kind"], "untyped")
        if fam["desc"]:
            lines.append(f"# HELP {name} {esc(fam['desc'])}")
        lines.append(f"# TYPE {name} {kind}")
        for m in fam["series"]:
            tag_str = ",".join(f'{k}="{esc(v)}"'
                               for k, v in sorted(m["tags"].items()))
            label = f"{{{tag_str}}}" if tag_str else ""
            if m["kind"] == "histogram" and m.get("buckets") is not None:
                cum = 0
                sep = "," if tag_str else ""
                for bound, n in zip(m["boundaries"], m["buckets"]):
                    cum += n
                    lines.append(
                        f'{name}_bucket{{{tag_str}{sep}le="{bound}"}} {cum}')
                cum += m["buckets"][-1]
                lines.append(f'{name}_bucket{{{tag_str}{sep}le="+Inf"}} {cum}')
                lines.append(f"{name}_sum{label} {m['sum']}")
                lines.append(f"{name}_count{label} {m['count']}")
            else:
                lines.append(f"{name}{label} {m['value']}")
    return "\n".join(lines) + "\n"


class Dashboard:
    """Serves cluster state as JSON over HTTP. Runs its own event-loop
    thread and a single controller connection; safe to start from any
    process that can reach the controller."""

    def __init__(self, address: str, host: str = "127.0.0.1", port: int = 8265):
        chost, cport = address.rsplit(":", 1)
        self._ctrl_addr = (chost, int(cport))
        self.host, self.port = host, port
        self._io = rpc.EventLoopThread(name="dashboard")
        self._conn: Optional[rpc.Connection] = None
        self._conn_lock: Optional[asyncio.Lock] = None
        self._runner = None

    async def _a_call(self, method: str, **kw):
        # Retry ONCE on a closed/severed controller connection: a
        # controller restart (or a mid-poll sever) must cost one failed
        # call, not a 500 on every panel until the dashboard process is
        # bounced (chaos-pinned in tests/test_chaos_telemetry.py).
        last_exc: Exception | None = None
        for attempt in range(2):
            if self._conn_lock is None:
                self._conn_lock = asyncio.Lock()
            async with self._conn_lock:  # concurrent handlers share one conn
                if self._conn is None or self._conn.closed:
                    self._conn = await rpc.connect(*self._ctrl_addr,
                                                   label="dashboard")
                    await self._conn.call("register", kind="client",
                                          worker_id=f"dashboard-{os.getpid()}",
                                          address=None)
                conn = self._conn
            try:
                return await conn.call(method, **kw)
            except (rpc.ConnectionClosed, ConnectionError, OSError) as e:
                last_exc = e
                async with self._conn_lock:
                    if self._conn is conn:  # don't drop a fresher reconnect
                        self._conn = None
        raise last_exc

    # ------------------------------------------------------------ server
    def start(self) -> int:
        """Bind and serve; returns the bound port."""

        async def _up():
            from aiohttp import web

            app = web.Application()
            app.router.add_get("/", self._index)
            app.router.add_get("/api/version", self._version)
            app.router.add_get("/api/cluster_status", self._cluster_status)
            app.router.add_get("/api/nodes", self._nodes)
            app.router.add_get("/api/actors", self._actors)
            app.router.add_get("/api/tasks", self._tasks)
            app.router.add_get("/api/objects", self._objects)
            app.router.add_get("/api/jobs", self._jobs)
            app.router.add_get("/api/events", self._events)
            app.router.add_get("/api/timeline", self._timeline)
            app.router.add_get("/api/timeseries", self._timeseries)
            app.router.add_get("/api/profiles", self._profiles)
            app.router.add_get("/api/traces", self._traces)
            app.router.add_get("/api/stacks", self._stacks)
            app.router.add_get("/api/metrics", self._metrics_json)
            app.router.add_get("/metrics", self._metrics_prom)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._runner = runner
            for s in site._server.sockets:  # resolve port=0
                self.port = s.getsockname()[1]
            return self.port

        return self._io.run(_up(), timeout=30)

    def stop(self):
        if self._runner is not None:
            async def _down():
                await self._runner.cleanup()
                if self._conn is not None:
                    await self._conn.close()

            try:
                self._io.run(_down(), timeout=10)
            except Exception:
                pass
        self._io.stop()

    # ---------------------------------------------------------- handlers
    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def _version(self, request):
        from aiohttp import web

        import ray_tpu

        return web.json_response({"ray_tpu": getattr(ray_tpu, "__version__", "dev"),
                                  "time": time.time()})

    async def _cluster_status(self, request):
        from aiohttp import web

        res = await self._a_call("cluster_resources")
        dem = await self._a_call("resource_demand")
        return web.json_response({
            "total": res["total"], "available": res["available"],
            "demand": dem["demand"], "pg_demand": dem["pg_demand"],
        })

    async def _nodes(self, request):
        from aiohttp import web

        snap = await self._a_call("state_snapshot")
        return web.json_response({"nodes": [
            {"node_id": nid, **info} for nid, info in snap["nodes"].items()]})

    async def _actors(self, request):
        from aiohttp import web

        snap = await self._a_call("state_snapshot")
        return web.json_response({"actors": [
            {"actor_id": aid, **info} for aid, info in snap["actors"].items()]})

    async def _tasks(self, request):
        from aiohttp import web

        limit = int(request.query.get("limit", 1000))
        rep = await self._a_call("list_tasks", limit=limit)
        return web.json_response({"tasks": rep["tasks"]})

    async def _objects(self, request):
        from aiohttp import web

        limit = int(request.query.get("limit", 1000))
        rep = await self._a_call("list_objects", limit=limit)
        return web.json_response({"objects": rep["objects"]})

    async def _jobs(self, request):
        from aiohttp import web

        rep = await self._a_call("list_jobs")
        return web.json_response({"jobs": rep["jobs"]})

    async def _stacks(self, request):
        """Live thread stacks of a worker:
        /api/stacks?worker_id=...[&node_id=...] (reference: the reporter
        agent's py-spy endpoints, dashboard/modules/reporter/)."""
        from aiohttp import web

        wid = request.query.get("worker_id")
        if not wid:
            return web.json_response(
                {"error": "worker_id query param required"}, status=400)
        rep = await self._a_call("worker_stacks", worker_id=wid,
                                 node_id=request.query.get("node_id"))
        return web.json_response(rep)

    async def _events(self, request):
        """Cluster event plane (README "Cluster events"):
        /api/events?entity=&kind=&severity=&since=&limit= — lifecycle
        history with seq-cursor polling (`next_seq` in the reply)."""
        from aiohttp import web

        kw: dict = {"limit": int(request.query.get("limit", 1000))}
        for key in ("entity", "kind", "severity"):
            if request.query.get(key):
                kw[key] = request.query[key]
        if request.query.get("since"):
            kw["since"] = int(request.query["since"])
        rep = await self._a_call("list_events", **kw)
        return web.json_response(rep)

    async def _timeseries(self, request):
        """Telemetry timeseries (README "Telemetry & profiling"):
        /api/timeseries?series=&node_id=&since= — series match exactly or
        by prefix (`node.` = family); needs a cluster running with
        RT_TELEMETRY_INTERVAL_S set."""
        from aiohttp import web

        kw = {}
        if request.query.get("series"):
            kw["series"] = request.query["series"]
        if request.query.get("node_id"):
            kw["node_id"] = request.query["node_id"]
        if request.query.get("since"):
            kw["since"] = float(request.query["since"])
        rep = await self._a_call("timeseries", **kw)
        return web.json_response(rep)

    async def _profiles(self, request):
        """Captured worker profiles: /api/profiles lists the registry;
        /api/profiles?name=<name-or-prefix> fetches one persisted profile
        document (collapsed stacks + Chrome-trace events)."""
        from aiohttp import web

        name = request.query.get("name")
        if not name:
            limit = int(request.query.get("limit", 1000))
            rep = await self._a_call("list_profiles", limit=limit)
            return web.json_response(rep)
        rep = await self._a_call("get_profile", name=name)
        if not rep.get("found"):
            return web.json_response(rep, status=404)
        return web.json_response(rep)

    async def _metrics_json(self, request):
        from aiohttp import web

        rep = await self._a_call("get_metrics")
        return web.json_response({"metrics": rep["metrics"]})

    async def _metrics_prom(self, request):
        """Prometheus exposition text (reference: the dashboard's metrics
        endpoint scraped by Prometheus)."""
        from aiohttp import web

        rep = await self._a_call("get_metrics")
        return web.Response(text=render_prometheus(rep["metrics"]),
                            content_type="text/plain")

    async def _traces(self, request):
        """Distributed-tracing index (README "Tracing & timeline"):
        /api/traces lists indexed traces; /api/traces?trace_id=... returns
        one trace rendered as Chrome-trace-event JSON (load the
        `traceEvents` doc in Perfetto), plus the raw spans."""
        from aiohttp import web

        tid = request.query.get("trace_id")
        if not tid:
            limit = int(request.query.get("limit", 1000))
            rep = await self._a_call("list_traces", limit=limit)
            return web.json_response({"traces": rep["traces"]})
        rep = await self._a_call("get_trace", trace_id=tid)
        if not rep.get("found"):
            return web.json_response(
                {"error": f"trace {tid!r} not found"}, status=404)
        from ray_tpu.scripts.cli import _chrome_trace_events

        events = _chrome_trace_events(rep["spans"])
        events.sort(key=lambda e: e.get("ts", 0.0))
        return web.json_response({
            "trace_id": rep.get("trace_id"), "name": rep.get("name"),
            "start": rep.get("start"), "end": rep.get("end"),
            "complete": rep.get("complete"), "spans": rep["spans"],
            "traceEvents": events, "displayTimeUnit": "ms"})

    async def _timeline(self, request):
        from aiohttp import web

        rep = await self._a_call("get_task_events")
        # Same chrome-trace shaping as ray_tpu.timeline() (reference
        # _private/state.py:965), rendered server-side for curl users.
        events = rep["events"]
        node_pid: dict[str, int] = {}
        trace: list[dict] = []
        for ev in events:
            pid = node_pid.setdefault(ev["node_id"], len(node_pid) + 1)
            trace.append({
                "ph": "X", "name": ev["name"], "cat": ev["kind"],
                "pid": pid, "tid": int(ev["pid"]),
                "ts": ev["start"] * 1e6,
                "dur": max(1.0, (ev["end"] - ev["start"]) * 1e6),
                "args": {"task_id": ev["task_id"], "ok": ev["ok"],
                         "attempt": ev["attempt"]},
            })
        return web.json_response(trace)


def start_dashboard(address: Optional[str] = None, host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    """Start a dashboard against `address` (or the current driver's
    cluster). Returns the running Dashboard (stop() when done)."""
    if address is None:
        address = os.environ.get("RT_ADDRESS")
    if address is None:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if w is not None:
            address = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
    if address is None:
        raise ValueError("no address: pass one, set RT_ADDRESS, or init() first")
    d = Dashboard(address, host, port)
    d.start()
    return d
