"""Dashboard: HTTP/JSON view of cluster state.

Parity target: reference python/ray/dashboard/head.py:46 (DashboardHead —
an aiohttp server aggregating GCS state for the web UI) with the module
endpoints that matter operationally (dashboard/modules/{node,actor,job,
state,reporter}): nodes, actors, tasks, objects, jobs, cluster status, and
a chrome-trace timeline. JSON only — point curl/a browser at it; the
reference's React frontend is intentionally out of scope.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from typing import Optional

from ray_tpu._private import rpc

logger = logging.getLogger(__name__)

_INDEX_HTML = """<html><head><title>ray_tpu dashboard</title></head><body>
<h2>ray_tpu dashboard</h2><ul>
<li><a href="/api/cluster_status">/api/cluster_status</a></li>
<li><a href="/api/nodes">/api/nodes</a></li>
<li><a href="/api/actors">/api/actors</a></li>
<li><a href="/api/tasks">/api/tasks</a></li>
<li><a href="/api/objects">/api/objects</a></li>
<li><a href="/api/jobs">/api/jobs</a></li>
<li><a href="/api/timeline">/api/timeline</a> (chrome trace; load in Perfetto)</li>
</ul></body></html>"""


class Dashboard:
    """Serves cluster state as JSON over HTTP. Runs its own event-loop
    thread and a single controller connection; safe to start from any
    process that can reach the controller."""

    def __init__(self, address: str, host: str = "127.0.0.1", port: int = 8265):
        chost, cport = address.rsplit(":", 1)
        self._ctrl_addr = (chost, int(cport))
        self.host, self.port = host, port
        self._io = rpc.EventLoopThread(name="dashboard")
        self._conn: Optional[rpc.Connection] = None
        self._conn_lock: Optional[asyncio.Lock] = None
        self._runner = None

    async def _a_call(self, method: str, **kw):
        if self._conn_lock is None:
            self._conn_lock = asyncio.Lock()
        async with self._conn_lock:  # concurrent handlers must share one conn
            if self._conn is None or self._conn.closed:
                self._conn = await rpc.connect(*self._ctrl_addr)
                await self._conn.call("register", kind="client",
                                      worker_id=f"dashboard-{os.getpid()}",
                                      address=None)
            conn = self._conn
        return await conn.call(method, **kw)

    # ------------------------------------------------------------ server
    def start(self) -> int:
        """Bind and serve; returns the bound port."""

        async def _up():
            from aiohttp import web

            app = web.Application()
            app.router.add_get("/", self._index)
            app.router.add_get("/api/version", self._version)
            app.router.add_get("/api/cluster_status", self._cluster_status)
            app.router.add_get("/api/nodes", self._nodes)
            app.router.add_get("/api/actors", self._actors)
            app.router.add_get("/api/tasks", self._tasks)
            app.router.add_get("/api/objects", self._objects)
            app.router.add_get("/api/jobs", self._jobs)
            app.router.add_get("/api/timeline", self._timeline)
            app.router.add_get("/api/metrics", self._metrics_json)
            app.router.add_get("/metrics", self._metrics_prom)
            runner = web.AppRunner(app, access_log=None)
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._runner = runner
            for s in site._server.sockets:  # resolve port=0
                self.port = s.getsockname()[1]
            return self.port

        return self._io.run(_up(), timeout=30)

    def stop(self):
        if self._runner is not None:
            async def _down():
                await self._runner.cleanup()
                if self._conn is not None:
                    await self._conn.close()

            try:
                self._io.run(_down(), timeout=10)
            except Exception:
                pass
        self._io.stop()

    # ---------------------------------------------------------- handlers
    async def _index(self, request):
        from aiohttp import web

        return web.Response(text=_INDEX_HTML, content_type="text/html")

    async def _version(self, request):
        from aiohttp import web

        import ray_tpu

        return web.json_response({"ray_tpu": getattr(ray_tpu, "__version__", "dev"),
                                  "time": time.time()})

    async def _cluster_status(self, request):
        from aiohttp import web

        res = await self._a_call("cluster_resources")
        dem = await self._a_call("resource_demand")
        return web.json_response({
            "total": res["total"], "available": res["available"],
            "demand": dem["demand"], "pg_demand": dem["pg_demand"],
        })

    async def _nodes(self, request):
        from aiohttp import web

        snap = await self._a_call("state_snapshot")
        return web.json_response({"nodes": [
            {"node_id": nid, **info} for nid, info in snap["nodes"].items()]})

    async def _actors(self, request):
        from aiohttp import web

        snap = await self._a_call("state_snapshot")
        return web.json_response({"actors": [
            {"actor_id": aid, **info} for aid, info in snap["actors"].items()]})

    async def _tasks(self, request):
        from aiohttp import web

        limit = int(request.query.get("limit", 1000))
        rep = await self._a_call("list_tasks", limit=limit)
        return web.json_response({"tasks": rep["tasks"]})

    async def _objects(self, request):
        from aiohttp import web

        limit = int(request.query.get("limit", 1000))
        rep = await self._a_call("list_objects", limit=limit)
        return web.json_response({"objects": rep["objects"]})

    async def _jobs(self, request):
        from aiohttp import web

        rep = await self._a_call("list_jobs")
        return web.json_response({"jobs": rep["jobs"]})

    async def _metrics_json(self, request):
        from aiohttp import web

        rep = await self._a_call("get_metrics")
        return web.json_response({"metrics": rep["metrics"]})

    async def _metrics_prom(self, request):
        """Prometheus exposition text (reference: the dashboard's metrics
        endpoint scraped by Prometheus)."""
        from aiohttp import web

        rep = await self._a_call("get_metrics")
        lines = []
        seen_help = set()

        def esc(v) -> str:
            # Prometheus label-value escaping: backslash, quote, newline.
            return (str(v).replace("\\", "\\\\").replace('"', '\\"')
                    .replace("\n", "\\n"))

        for m in rep["metrics"]:
            name = m["name"].replace(".", "_").replace("-", "_")
            if name not in seen_help:
                seen_help.add(name)
                kind = {"counter": "counter", "gauge": "gauge",
                        "histogram": "histogram"}[m["kind"]]
                if m.get("desc"):
                    lines.append(f"# HELP {name} {m['desc']}")
                lines.append(f"# TYPE {name} {kind}")
            tag_str = ",".join(f'{k}="{esc(v)}"' for k, v in sorted(m["tags"].items()))
            label = f"{{{tag_str}}}" if tag_str else ""
            if m["kind"] == "histogram" and m.get("buckets") is not None:
                cum = 0
                for bound, n in zip(m["boundaries"], m["buckets"]):
                    cum += n
                    sep = "," if tag_str else ""
                    lines.append(
                        f'{name}_bucket{{{tag_str}{sep}le="{bound}"}} {cum}')
                cum += m["buckets"][-1]
                sep = "," if tag_str else ""
                lines.append(f'{name}_bucket{{{tag_str}{sep}le="+Inf"}} {cum}')
                lines.append(f"{name}_sum{label} {m['sum']}")
                lines.append(f"{name}_count{label} {m['count']}")
            else:
                lines.append(f"{name}{label} {m['value']}")
        return web.Response(text="\n".join(lines) + "\n",
                            content_type="text/plain")

    async def _timeline(self, request):
        from aiohttp import web

        rep = await self._a_call("get_task_events")
        # Same chrome-trace shaping as ray_tpu.timeline() (reference
        # _private/state.py:965), rendered server-side for curl users.
        events = rep["events"]
        node_pid: dict[str, int] = {}
        trace: list[dict] = []
        for ev in events:
            pid = node_pid.setdefault(ev["node_id"], len(node_pid) + 1)
            trace.append({
                "ph": "X", "name": ev["name"], "cat": ev["kind"],
                "pid": pid, "tid": int(ev["pid"]),
                "ts": ev["start"] * 1e6,
                "dur": max(1.0, (ev["end"] - ev["start"]) * 1e6),
                "args": {"task_id": ev["task_id"], "ok": ev["ok"],
                         "attempt": ev["attempt"]},
            })
        return web.json_response(trace)


def start_dashboard(address: Optional[str] = None, host: str = "127.0.0.1",
                    port: int = 8265) -> Dashboard:
    """Start a dashboard against `address` (or the current driver's
    cluster). Returns the running Dashboard (stop() when done)."""
    if address is None:
        address = os.environ.get("RT_ADDRESS")
    if address is None:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if w is not None:
            address = f"{w.controller_addr[0]}:{w.controller_addr[1]}"
    if address is None:
        raise ValueError("no address: pass one, set RT_ADDRESS, or init() first")
    d = Dashboard(address, host, port)
    d.start()
    return d
