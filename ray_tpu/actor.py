"""Actors: stateful remote workers.

Parity target: reference python/ray/actor.py (ActorClass:617,
ActorClass._remote:907, ActorHandle:1287, ActorMethod:116) — named actors,
max_restarts, get_if_exists; handles pickle across processes and re-resolve
via the controller (reference: actor table in GCS, gcs_actor_manager).
"""

from __future__ import annotations

from typing import Any

from ray_tpu._private.resources import normalize_resources
from ray_tpu._private.task_spec import SchedulingStrategy
from ray_tpu._private.worker import global_worker
from ray_tpu.remote_function import _to_strategy

_ACTOR_OPTION_KEYS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "memory", "name", "namespace",
    "get_if_exists", "max_restarts", "max_task_retries", "max_concurrency",
    "scheduling_strategy", "lifetime", "runtime_env", "placement_group",
    "placement_group_bundle_index", "concurrency_groups",
}


def method(*, concurrency_group: str | None = None, num_returns: int | None = None):
    """Method decorator (reference python/ray/actor.py @ray.method): tags an
    actor method with a concurrency group and/or return arity."""

    def deco(fn):
        if concurrency_group is not None:
            fn._rt_concurrency_group = concurrency_group
        if num_returns is not None:
            fn._rt_num_returns = num_returns
        return fn

    return deco


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns: int = 1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def options(self, num_returns: int = 1):
        return ActorMethod(self._handle, self._name, num_returns)

    def remote(self, *args, **kwargs):
        w = global_worker()
        refs = w.submit_actor_task(
            self._handle._actor_id,
            self._name,
            args,
            kwargs,
            num_returns=self._num_returns,
            max_task_retries=self._handle._max_task_retries,
        )
        return refs[0] if self._num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        """Compiled-graph binding of this EXISTING actor's method
        (reference actor.method.bind -> dag.DAGNode); compile() attaches a
        channel execution loop to the actor."""
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor method {self._name!r} must be called with .remote().")


class ActorHandle:
    def __init__(self, actor_id: str, max_task_retries: int = 0,
                 method_meta: dict | None = None):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries
        # method name -> num_returns from @ray_tpu.method(num_returns=...)
        # (introspected at ActorClass.remote; rides pickled handles).
        self._method_meta = method_meta or {}

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        # Cache in the instance dict: the next `handle.method` skips
        # __getattr__ (and the ActorMethod alloc) entirely — actor call
        # dispatch is a hot path.
        m = ActorMethod(self, name, self._method_meta.get(name, 1))
        self.__dict__[name] = m
        return m

    def __repr__(self):
        return f"ActorHandle({self._actor_id[:12]})"

    def __reduce__(self):
        # NB: cached ActorMethods in __dict__ are deliberately not pickled.
        return (ActorHandle,
                (self._actor_id, self._max_task_retries, self._method_meta))

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


class ActorClass:
    def __init__(self, cls, options: dict[str, Any] | None = None):
        self._cls = cls
        self._options = dict(options or {})

    def options(self, **overrides) -> "ActorClass":
        bad = set(overrides) - _ACTOR_OPTION_KEYS
        if bad:
            raise ValueError(f"Unknown actor options: {sorted(bad)}")
        merged = dict(self._options)
        merged.update(overrides)
        return ActorClass(self._cls, merged)

    def remote(self, *args, **kwargs) -> ActorHandle:
        w = global_worker()
        if w is None:
            raise RuntimeError("ray_tpu.init() must be called before .remote()")
        o = self._options
        lifetime = o.get("lifetime")
        if lifetime not in (None, "detached", "non_detached"):
            raise ValueError(f"lifetime must be None, 'detached' or 'non_detached', got {lifetime!r}")
        # Non-detached actors fate-share with a driver/actor owner
        # (controller _reap_owned_actors); 'detached' opts out.
        num_tpus = o.get("num_tpus", o.get("num_gpus"))
        resources = normalize_resources(
            num_cpus=o.get("num_cpus"),
            num_tpus=num_tpus,
            resources=o.get("resources"),
            memory=o.get("memory"),
            default_cpus=1.0,
        )
        strategy = _to_strategy(o.get("scheduling_strategy"))
        pg = o.get("placement_group")
        if pg is not None:
            strategy = SchedulingStrategy(
                kind="PLACEMENT_GROUP",
                pg_id=pg.id if hasattr(pg, "id") else pg,
                pg_bundle_index=o.get("placement_group_bundle_index", -1),
            )
        actor_id = w.create_actor(
            self._cls,
            args,
            kwargs,
            name=o.get("name"),
            namespace=o.get("namespace", "default"),
            get_if_exists=o.get("get_if_exists", False),
            resources=resources,
            strategy=strategy,
            max_restarts=o.get("max_restarts", 0),
            max_task_retries=o.get("max_task_retries", 0),
            max_concurrency=o.get("max_concurrency", 1),
            concurrency_groups=o.get("concurrency_groups"),
            runtime_env=o.get("runtime_env"),
            actor_display_name=self._cls.__name__,
            lifetime=None if lifetime == "non_detached" else lifetime,
        )
        meta = {name: getattr(fn, "_rt_num_returns")
                for name, fn in vars(self._cls).items()
                if callable(fn) and hasattr(fn, "_rt_num_returns")}
        return ActorHandle(actor_id, max_task_retries=o.get("max_task_retries", 0),
                           method_meta=meta)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__!r} cannot be instantiated directly; "
            f"use {self._cls.__name__}.remote()."
        )


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    w = global_worker()
    rep = w.io.run(w.controller.call("get_actor_info", name=name, namespace=namespace, wait=False))
    if rep["status"] != "ok":
        raise ValueError(f"Failed to look up actor {name!r} in namespace {namespace!r}")
    return ActorHandle(rep["actor_id"], max_task_retries=rep.get("max_task_retries", 0))


def kill(actor: ActorHandle, *, no_restart: bool = True):
    w = global_worker()
    w.kill_actor(actor._actor_id, no_restart=no_restart)
