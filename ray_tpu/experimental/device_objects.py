"""Device objects (experimental): actor-resident `jax.Array` ObjectRefs.

Parity target: the reference runtime's direct-transport design for GPU
objects (`ray.experimental` GPU objects / compiled-graph direct transports):
device-resident values stay behind ObjectRefs in the producing actor and
move peer-to-peer, instead of round-tripping host -> object store -> host.
See `ray_tpu._private.device_store` for the mechanism and README "Device
objects" for the tiering / ownership / fallback rules.

With the plane enabled (default; `RT_DEVICE_OBJECTS=0` disables), any
single-device `jax.Array` at or above `RT_DEVICE_OBJECT_MIN_BYTES` returned
from a task/actor or passed to `ray_tpu.put()` rides it automatically —
there is nothing to call. This module is the introspection surface.
"""

from __future__ import annotations

from ray_tpu._private import device_store
from ray_tpu._private.rtconfig import CONFIG


def is_enabled() -> bool:
    """Whether the device object plane is on in this process
    (`RT_DEVICE_OBJECTS` / `_system_config={"device_objects": ...}`)."""
    return bool(CONFIG.device_objects)


def device_object_stats() -> dict:
    """This process's DeviceObjectTable residency: `{"count", "bytes"}` of
    arrays currently pinned by objects this process produced. The
    cluster-wide view is the `rt_device_objects_{count,bytes}` gauges
    (`ray_tpu.util.state.metrics()`) and the `plane` column of
    `ray_tpu.util.state.list_objects()`."""
    return device_store.table_stats()


def would_ride_device_plane(value) -> bool:
    """Whether `value` would be pinned device-side if returned from a task
    or actor right now (type/size/sharding gates included)."""
    return device_store.eligible(value)
