"""Mutable shared-memory channels: the zero-copy low-latency substrate.

Parity target: reference experimental/channel/shared_memory_channel.py
backed by src/ray/core_worker/experimental_mutable_object_manager.h —
fixed shm segments REUSED for every message, so steady-state transfer does
no allocation, no RPC, and no scheduling. SPSC with a seq/ack pair in the
header: the writer blocks until the reader acked the previous message
(capacity-1 backpressure), the reader blocks until seq advances.

Blocking strategy: when the native library (ray_tpu/_native/ring.cc) is
available both ends sleep in the kernel on futex words embedded in the
header — the reference's C++ mutable-object waiter, TPU-host edition. The
pure-Python fallback sleep-polls the same header layout, so mixed
native/Python endpoints interoperate (native waits are bounded, so a peer
that never calls futex_wake only costs ~2 ms of latency, not a hang).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import pickle
import struct
import time

# header (64B): [seq u64][ack u64][size u64][wseq u32][wack u32][reserved]
# data starts at _DATA. Must match ray_tpu/_native/ring.cc::Hdr.
_HDR = struct.Struct("<QQQII")
_DATA = 64


def _native():
    from ray_tpu._native import get_lib

    return get_lib()


class Channel:
    """One named SPSC channel over /dev/shm. Both ends open by name; the
    handle pickles as (name, size) so it can ride task/actor args."""

    def __init__(self, name: str, size: int = 1 << 20, _create: bool = True):
        self.name = name
        self.size = size
        self._path = os.path.join("/dev/shm", f"rtch_{name}")
        total = _DATA + size
        if _create:
            exists = os.path.exists(self._path)
            fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o600)
            try:
                if not exists or os.fstat(fd).st_size != total:
                    # Fresh segment, or a stale same-named file from a
                    # crashed run whose size disagrees: (re)size it. The
                    # creator owns the layout.
                    os.ftruncate(fd, total)
            except Exception:
                os.close(fd)
                raise
        else:
            # Attach STRICTLY: no O_CREAT. An attacher racing a teardown
            # unlink must fail loudly instead of silently re-creating an
            # orphan segment nobody will ever unlink again.
            fd = os.open(self._path, os.O_RDWR)
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        self._lib = _native()
        self._view = (ctypes.c_char * total).from_buffer(self._mm)
        self._addr = ctypes.addressof(self._view)
        # Reader joins at the ACK point: a message written before this end
        # attached is still pending and must be delivered (the head would
        # silently skip it and deadlock the backpressured writer).
        self._last_read = self._ack()

    # ------------------------------------------------------------- header
    def _seq(self) -> int:
        return _HDR.unpack_from(self._mm, 0)[0]

    def _ack(self) -> int:
        return _HDR.unpack_from(self._mm, 0)[1]

    def _set(self, seq=None, ack=None, size=None):
        s, a, z, _, _ = _HDR.unpack_from(self._mm, 0)
        s = s if seq is None else seq
        a = a if ack is None else ack
        z = z if size is None else size
        # Futex mirror words ride along so native peers' kernel waits see
        # the transition (they re-check at a bounded interval regardless).
        _HDR.pack_into(self._mm, 0, s, a, z,
                       s & 0xFFFFFFFF, a & 0xFFFFFFFF)

    # -------------------------------------------------------------- write
    def write(self, value, timeout: float | None = None):
        blob = pickle.dumps(value, protocol=5)
        if len(blob) > self.size:
            raise ValueError(f"message {len(blob)}B > channel size {self.size}B")
        if self._lib is not None:
            ns = -1 if timeout is None else int(timeout * 1e9)
            rc = self._lib.rt_ring_write(self._addr, self.size, blob,
                                         len(blob), ns)
            if rc == -1:
                raise TimeoutError("channel write timed out (reader stalled)")
            if rc != 0:
                raise ValueError(f"channel write failed (rc={rc})")
            return
        deadline = None if timeout is None else time.monotonic() + timeout
        seq = self._seq()
        # backpressure: previous message must be consumed
        while self._ack() < seq:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out (reader stalled)")
            time.sleep(0.000005)
        self._mm[_DATA:_DATA + len(blob)] = blob
        # Publish order matters for native readers (they wake on the seq
        # transition and then load size): size first, then seq.
        struct.pack_into("<Q", self._mm, 16, len(blob))
        self._set(seq=seq + 1)

    # --------------------------------------------------------------- read
    def read(self, timeout: float | None = None):
        if self._lib is not None:
            ns = -1 if timeout is None else int(timeout * 1e9)
            n = self._lib.rt_ring_wait(self._addr, self._last_read, ns)
            if n == -1:
                raise TimeoutError("channel read timed out")
            blob = bytes(self._mm[_DATA:_DATA + n])
            self._last_read = self._seq()
            self._lib.rt_ring_ack(self._addr)
            return pickle.loads(blob)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._seq()
            if seq > self._last_read:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            time.sleep(0.000005)
        size = _HDR.unpack_from(self._mm, 0)[2]
        blob = bytes(self._mm[_DATA:_DATA + size])
        self._last_read = seq
        self._set(ack=seq)
        return pickle.loads(blob)

    def close(self, unlink: bool = False):
        # The ctypes from_buffer view must die before mmap.close() accepts.
        self._view = None
        self._addr = None
        try:
            self._mm.close()
        except Exception:
            pass
        if unlink:
            try:
                os.unlink(self._path)
            except OSError:
                pass

    def __reduce__(self):
        return (Channel, (self.name, self.size, False))
