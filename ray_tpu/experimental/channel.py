"""Mutable shared-memory channels: the zero-copy low-latency substrate.

Parity target: reference experimental/channel/shared_memory_channel.py
backed by src/ray/core_worker/experimental_mutable_object_manager.h —
fixed shm segments REUSED for every message, so steady-state transfer does
no allocation, no RPC, and no scheduling. SPSC with a seq/ack pair in the
header: the writer blocks until the reader acked the previous message
(capacity-1 backpressure), the reader blocks until seq advances.
"""

from __future__ import annotations

import mmap
import os
import pickle
import struct
import time

# header: [seq: u64][ack: u64][size: u64]
_HDR = struct.Struct("<QQQ")


class Channel:
    """One named SPSC channel over /dev/shm. Both ends open by name; the
    handle pickles as (name, size) so it can ride task/actor args."""

    def __init__(self, name: str, size: int = 1 << 20, _create: bool = True):
        self.name = name
        self.size = size
        self._path = os.path.join("/dev/shm", f"rtch_{name}")
        total = _HDR.size + size
        exists = os.path.exists(self._path)
        fd = os.open(self._path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            if not exists:
                os.ftruncate(fd, total)
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        # Reader joins at the ACK point: a message written before this end
        # attached is still pending and must be delivered (the head would
        # silently skip it and deadlock the backpressured writer).
        self._last_read = self._ack()

    # ------------------------------------------------------------- header
    def _seq(self) -> int:
        return _HDR.unpack_from(self._mm, 0)[0]

    def _ack(self) -> int:
        return _HDR.unpack_from(self._mm, 0)[1]

    def _set(self, seq=None, ack=None, size=None):
        s, a, z = _HDR.unpack_from(self._mm, 0)
        _HDR.pack_into(self._mm, 0,
                       s if seq is None else seq,
                       a if ack is None else ack,
                       z if size is None else size)

    # -------------------------------------------------------------- write
    def write(self, value, timeout: float | None = None):
        blob = pickle.dumps(value, protocol=5)
        if len(blob) > self.size:
            raise ValueError(f"message {len(blob)}B > channel size {self.size}B")
        deadline = None if timeout is None else time.monotonic() + timeout
        seq = self._seq()
        # backpressure: previous message must be consumed
        while self._ack() < seq:
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel write timed out (reader stalled)")
            time.sleep(0.000005)
        self._mm[_HDR.size:_HDR.size + len(blob)] = blob
        self._set(seq=seq + 1, size=len(blob))

    # --------------------------------------------------------------- read
    def read(self, timeout: float | None = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            seq = self._seq()
            if seq > self._last_read:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError("channel read timed out")
            time.sleep(0.000005)
        size = _HDR.unpack_from(self._mm, 0)[2]
        blob = bytes(self._mm[_HDR.size:_HDR.size + size])
        self._last_read = seq
        self._set(ack=seq)
        return pickle.loads(blob)

    def close(self, unlink: bool = False):
        try:
            self._mm.close()
        except Exception:
            pass
        if unlink:
            try:
                os.unlink(self._path)
            except OSError:
                pass

    def __reduce__(self):
        return (Channel, (self.name, self.size, False))
