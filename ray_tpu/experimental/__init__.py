"""Experimental surfaces (reference ray.experimental): compiled-graph
channels (`channel`) and the device object plane (`device_objects`)."""

from ray_tpu.experimental import device_objects  # noqa: F401
