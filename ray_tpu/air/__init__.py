"""ray_tpu.air — shared config/result surface (reference python/ray/air:
air/config.py ScalingConfig/RunConfig/FailureConfig/CheckpointConfig,
air/result.py Result). Canonical definitions live in ray_tpu.train."""

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._internal.controller import Result
from ray_tpu.air import session

__all__ = ["Checkpoint", "CheckpointConfig", "FailureConfig", "RunConfig",
           "ScalingConfig", "Result", "session"]
