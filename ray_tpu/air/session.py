"""air.session — the unified in-trainer session surface.

Parity target: reference python/ray/air/session.py (report, get_checkpoint,
get_dataset_shard, get_world_rank/size — thin delegation to whichever
session is active: a train worker session or a tune trial session).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.train.checkpoint import Checkpoint


def _train_session():
    from ray_tpu.train._internal.session import _session

    return _session


def _tune_session():
    from ray_tpu.tune import _session as tune_session

    return tune_session._session


def report(metrics: dict, checkpoint: Optional[Checkpoint] = None):
    s = _train_session()
    if s is not None:
        return s.report(metrics, checkpoint)
    t = _tune_session()
    if t is not None:
        return t.report(metrics, checkpoint)
    raise RuntimeError("air.session.report() outside a train/tune session")


def get_checkpoint() -> Optional[Checkpoint]:
    s = _train_session()
    if s is not None:
        return s.get_checkpoint()
    t = _tune_session()
    if t is not None:
        return t.get_checkpoint()
    return None


def get_dataset_shard(name: str = "train"):
    s = _train_session()
    if s is None:
        raise RuntimeError("no train session")
    return s.get_dataset_shard(name)


def get_world_rank() -> int:
    s = _train_session()
    return 0 if s is None else s.rank


def get_world_size() -> int:
    s = _train_session()
    return 1 if s is None else s.world_size


def get_local_rank() -> int:
    s = _train_session()
    return 0 if s is None else s.local_rank
