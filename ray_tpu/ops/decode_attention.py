"""Pallas decode attention: single-token queries against a KV cache.

The serving hot loop is q=[B, 1, H, D] attending over a fixed [B, S, KV, D]
cache with per-sequence valid lengths — shapes the prefill flash kernel
rejects (Sq=1 violates its q-block tiling), which previously forced the
O(Sq*Sk)-materializing XLA fallback every decode step (the r04 bench
warning). This kernel blocks only the cache axis: one grid program per
(batch, kv-head) pair streams the cache in VMEM-sized chunks, carrying
f32 online-softmax state in scratch, with the per-sequence length applied
as a column mask. GQA folds the q-head group for a kv head into the
sublane axis of a single [rep, D] tile.

Reference role: vLLM's paged-attention decode kernel (the engine seat
python/ray/llm delegates; no TPU equivalent exists in the reference).
"""

from __future__ import annotations

import functools
import logging

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

NEG_INF = float("-inf")
DEFAULT_BLOCK_K = 512


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                   acc_ref, *, scale: float, block_k: int, n_k_blocks: int):
    ki = pl.program_id(1)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    k_start = ki * block_k
    # lengths live whole-array in SMEM (scalars can't tile into VMEM blocks)
    length = len_ref[pl.program_id(0)]

    @pl.when(k_start < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [rep, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [rep, block_k]
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        s = jnp.where(cols < length, s, NEG_INF)
        m_prev = m_ref[:, 0:1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("block_k", "interpret"))
def decode_attention_pallas(q, k_cache, v_cache, lengths, *,
                            block_k: int = DEFAULT_BLOCK_K,
                            interpret: bool = False):
    """q: [B, H, D] (one new token per sequence); k/v_cache: [B, S, KV, D];
    lengths: [B] int32 — rows [0, lengths[b]) of sequence b's cache are
    valid (INCLUDING the just-written current token). Returns [B, H, D]."""
    b, hq, d = q.shape
    _, sk, hkv, _ = k_cache.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    rep = hq // hkv
    block_k = min(block_k, sk)
    if sk % block_k or block_k % 128:
        raise ValueError(
            f"cache length {sk} not divisible by lane-aligned block "
            f"{block_k}")
    scale = d ** -0.5
    n_k = sk // block_k
    # Pad the per-kv-head q group up to the 8-row sublane tile: padded rows
    # are zeros (scores 0 -> uniform softmax -> finite garbage, sliced off).
    rep_pad = max(rep, 8)

    # [B*KV, rep_pad, D] q tiles; [B*KV, S, D] cache views.
    qt = q.reshape(b, hkv, rep, d).reshape(b * hkv, rep, d)
    if rep_pad != rep:
        qt = jnp.pad(qt, ((0, 0), (0, rep_pad - rep), (0, 0)))
    kt = k_cache.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v_cache.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    lens = jnp.broadcast_to(
        lengths.astype(jnp.int32)[:, None], (b, hkv)).reshape(b * hkv)

    kernel = functools.partial(
        _decode_kernel, scale=scale, block_k=block_k, n_k_blocks=n_k)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * hkv, rep_pad, d), q.dtype),
        grid=(b * hkv, n_k),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # lengths, whole array
            pl.BlockSpec((1, rep_pad, d), lambda bh, ki: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, ki: (bh, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, rep_pad, d), lambda bh, ki: (bh, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((rep_pad, 128), jnp.float32),  # running max
            pltpu.VMEM((rep_pad, 128), jnp.float32),  # running denom
            pltpu.VMEM((rep_pad, d), jnp.float32),    # accumulator
        ],
        interpret=interpret,
    )(lens, qt, kt, vt)
    if rep_pad == rep:
        return out.reshape(b, hq, d)
    return out[:, :rep].reshape(b, hq, d)


def _xla_decode_attention(q, k_cache, v_cache, lengths):
    """Reference path (any backend): masked dense attention over the cache."""
    b, hq, d = q.shape
    _, sk, hkv, _ = k_cache.shape
    if hkv < hq:
        repn = hq // hkv
        k_cache = jnp.repeat(k_cache, repn, axis=2)
        v_cache = jnp.repeat(v_cache, repn, axis=2)
    scores = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32),
                        k_cache.astype(jnp.float32)) / (d ** 0.5)
    mask = jnp.arange(sk)[None, None, :] < lengths[:, None, None]
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bht,bthd->bhd", probs, v_cache.astype(jnp.float32))
    return out.astype(q.dtype)


_warned = False

#: Cache bytes above which the Pallas kernel dispatches by default. At
#: serving-typical sizes (B=8, KV=16, D=64, S=1024: ~2x16MB bf16) the
#: fused XLA einsum WINS — measured 1.44 vs 2.83 ms per 8-layer decode
#: step on v5e: per-layer pallas_call launch overhead dominates when the
#: per-head score row is only [1, S]. The kernel's streaming VMEM schedule
#: pays off once the per-call cache traffic is large enough to amortize
#: launches (long context / big batch). RT_DECODE_KERNEL=pallas|xla
#: overrides.
PALLAS_MIN_CACHE_BYTES = 256 * 1024 * 1024


def decode_attention(q, k_cache, v_cache, lengths, *, interpret: bool = False):
    """Dispatcher: size-based choice between the fused XLA path and the
    Pallas streaming kernel (env RT_DECODE_KERNEL forces one).
    q: [B, H, D]; caches [B, S, KV, D]; lengths [B] -> [B, H, D]."""
    global _warned
    from ray_tpu._private.rtconfig import CONFIG

    force = str(CONFIG.decode_kernel).lower()
    on_tpu = jax.devices()[0].platform == "tpu"
    cache_bytes = 2 * k_cache.size * k_cache.dtype.itemsize
    want_pallas = (force == "pallas"
                   or (force != "xla"
                       and cache_bytes >= PALLAS_MIN_CACHE_BYTES))
    if (on_tpu and want_pallas) or interpret:
        try:
            return decode_attention_pallas(
                q, k_cache, v_cache, lengths, interpret=interpret)
        except Exception as e:
            if not _warned:
                _warned = True
                logger.warning("decode attention falling back to XLA: %s", e)
    return _xla_decode_attention(q, k_cache, v_cache, lengths)
