"""Ring attention: sequence-parallel attention over a mesh axis.

Each device holds a [B, S/n, H, D] shard of q/k/v along the sequence axis.
k/v shards rotate around the ring via `ppermute` while every device folds
the visiting chunk into its queries' online-softmax state (m, l, acc in
f32), so the full [Sq, Sk] score matrix never exists anywhere and the k/v
memory per device stays O(S/n) — the long-context mechanism SURVEY §7
step 11 calls for (the reference has no equivalent; it delegates long
context to vLLM). Designed for use inside shard_map over the 'sp' mesh
axis; collectives ride ICI.

Causality uses GLOBAL positions: shard i's queries own rows
[i*S/n, (i+1)*S/n); the chunk visiting at step s carries the keys of shard
(i - s) mod n, so whole future chunks contribute nothing (their
exp(-inf)=0) and the math matches single-device causal attention exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True):
    """q/k/v: local shards [B, S_local, H, D] of a sequence sharded over
    `axis_name`. Returns the local output shard [B, S_local, H, D]. Call
    inside shard_map/pjit with q/k/v sharded on the sequence axis."""
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, s_local, hq, d = q.shape
    _, _, hkv, _ = k.shape
    rep = hq // hkv
    scale = d ** -0.5
    # GQA stays folded as a group dim [b, s, hkv, rep, d]: k/v ride the
    # ring at their NATIVE hkv width (repeating them would multiply every
    # ppermute transfer and per-device kv residency by hq/hkv).
    qf = q.astype(jnp.float32).reshape(b, s_local, hkv, rep, d)

    q_pos = idx * s_local + jax.lax.broadcasted_iota(
        jnp.int32, (s_local, s_local), 0)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, s):
        m, l, acc, k_cur, v_cur = carry
        owner = (idx - s) % n  # whose keys are visiting this step
        sc = jnp.einsum("bqhgd,bkhd->bhgqk", qf, k_cur.astype(jnp.float32),
                        preferred_element_type=jnp.float32) * scale
        if causal:
            k_pos = owner * s_local + jax.lax.broadcasted_iota(
                jnp.int32, (s_local, s_local), 1)
            mask = k_pos <= q_pos  # [s_local, s_local] global causal
            sc = jnp.where(mask[None, None, None], sc, jnp.float32(-jnp.inf))
        m_cur = jnp.max(sc, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        # Guard -inf - -inf (rows with no visible keys in this chunk).
        p = jnp.exp(sc - jnp.where(jnp.isinf(m_new), 0.0, m_new))
        p = jnp.where(jnp.isinf(m_new), 0.0, p)
        alpha = jnp.exp(m - m_new)
        alpha = jnp.where(jnp.isinf(m) & jnp.isinf(m_new), 0.0, alpha)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jnp.einsum(
            "bhgqk,bkhd->bhgqd", p, v_cur.astype(jnp.float32))
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m_new, l_new, acc_new, k_nxt, v_nxt), None

    m0 = jnp.full((b, hkv, rep, s_local, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((b, hkv, rep, s_local, 1), jnp.float32)
    acc0 = jnp.zeros((b, hkv, rep, s_local, d), jnp.float32)
    # The outputs vary over the sp axis (they depend on axis_index); the
    # constant initial carries must be marked varying too or scan rejects
    # the carry type under shard_map.
    for _mark in (lambda x: jax.lax.pcast(x, to="varying"),
                  lambda x: jax.lax.pvary(x, axis_name),
                  lambda x: x):
        # Marking API differs across jax versions (pcast / pvary), and jax
        # builds WITHOUT either (<=0.4.x) don't type-check carry variance
        # under shard_map at all — the identity fallback is correct there.
        try:
            m0, l0, acc0 = (_mark(x) for x in (m0, l0, acc0))
            break
        except (AttributeError, TypeError):
            continue
    (m, l, acc, _k, _v), _ = jax.lax.scan(
        step, (m0, l0, acc0, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / l).astype(q.dtype)  # [B, Hkv, rep, Sq_local, D]
    return out.transpose(0, 3, 1, 2, 4).reshape(b, s_local, hq, d)


# `ray_tpu.ops.ring_attention` names BOTH this submodule and the lazily
# re-exported function in the package namespace; importing this module
# rebinds the package attribute to the module object (import machinery
# setattr), which would turn `ray_tpu.ops.ring_attention(q, k, v)` into a
# TypeError depending on import order. Making the module itself callable
# keeps both access patterns working in every order.
import sys as _sys
import types as _types


class _CallableModule(_types.ModuleType):
    def __call__(self, *args, **kwargs):
        return ring_attention(*args, **kwargs)


_sys.modules[__name__].__class__ = _CallableModule
