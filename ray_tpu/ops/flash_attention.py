"""Pallas flash attention for TPU.

Blockwise online-softmax attention (Flash Attention 2 schedule): the k/v
sequence axis is the innermost grid dimension, with the running max /
denominator / accumulator carried in VMEM scratch across grid steps (TPU
grids execute sequentially per core, so scratch persists). Softmax state is
f32 regardless of input dtype; the [Sq, Sk] score matrix never
materializes, so memory is O(Sq * D) instead of O(Sq * Sk).

The reference framework ships no attention kernels (it delegates to
torch/vLLM); this is the TPU-native equivalent of that delegated surface.
Interpret mode makes the same kernel testable on the CPU mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Tuned on v5e (4x2048x8x128 bf16 causal: 128/128 -> 13 TFLOP/s useful,
# 512/1024 -> ~72 TFLOP/s): bigger k blocks amortize the per-step softmax
# state rescale; q=512 keeps q+k+v+acc well inside VMEM.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 1024
NEG_INF = float("-inf")


def _auto_block(dim: int, preferred: int, align: int) -> int | None:
    """Largest divisor of `dim` that is a multiple of `align` (TPU sublane/
    lane tiling) and <= `preferred`. None when no aligned divisor exists
    (the shape then falls back to the XLA path). Auto-deriving from the
    input shape keeps the tuned defaults for big sequences while accepting
    any lane-alignable Sq/Sk — e.g. Sq=Sk=640 picks 320/640, not a
    hard-coded 512/1024 that 640 doesn't divide."""
    if dim % align:
        return None
    best = None
    for cand in range(align, min(preferred, dim) + 1, align):
        if dim % cand == 0:
            best = cand
    return best


def derive_blocks(sq: int, sk: int, block_q: int | None = None,
                  block_k: int | None = None) -> tuple[int, int]:
    """Resolve the (block_q, block_k) pair for a [Sq, Sk] problem, CLAMPED
    to valid TPU tiles — block_q on the sublane grid (8), block_k on the
    lane grid (128). Explicit blocks are treated as preferences (upper
    bounds) and re-clamped the same way, so a caller-supplied 1024 against
    a short sequence can never squeeze past the divisibility check as a
    tile-violating remnant (the r05 bench regression: a raw min() clamp
    produced blocks like 8/8 and the opaque "violate TPU tiling" reason).
    Raises ValueError with the fallback reason when no valid tile exists —
    the dispatcher's cue to take the XLA path."""
    bq = _auto_block(sq, block_q or DEFAULT_BLOCK_Q, 8)
    if bq is None:
        raise ValueError(
            f"Sq={sq} has no divisor aligned to the TPU sublane tile (8)"
            + (f" at or under block_q={block_q}" if block_q else ""))
    bk = _auto_block(sk, block_k or DEFAULT_BLOCK_K, 128)
    if bk is None:
        # block_k spans the LANE axis of the [block_q, block_k] score
        # tile, so it needs 128-alignment (block_q only needs sublane 8).
        raise ValueError(
            f"Sk={sk} has no divisor aligned to the TPU lane tile (128)"
            + (f" at or under block_k={block_k}" if block_k else ""))
    return bq, bk


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *,
                  scale: float, causal: bool, block_q: int, block_k: int,
                  n_k_blocks: int, diag_offset: int):
    """diag_offset = Sk - Sq: query row i attends to keys <= i + offset
    (matches _xla_attention's tril(k=sk-sq) alignment)."""
    qi = pl.program_id(2)
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    q_start = qi * block_q
    k_start = ki * block_k

    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [block_q, D]
        k = k_ref[0].astype(jnp.float32)  # [block_k, D]
        v = v_ref[0].astype(jnp.float32)  # [block_k, D]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # [block_q, block_k]
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows + diag_offset, s, NEG_INF)
        m_prev = m_ref[:, 0:1]  # [block_q, 1]
        l_prev = l_ref[:, 0:1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)  # exp(-inf)=0 handles fully-masked cols
        alpha = jnp.exp(m_prev - m_new)  # [block_q, 1]
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # Skip blocks entirely above the (offset) diagonal.
        pl.when(k_start <= q_start + diag_offset + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(ki == n_k_blocks - 1)
    def _finish():
        l = l_ref[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"))
def flash_attention(q, k, v, *, causal: bool = True,
                    block_q: int | None = None,
                    block_k: int | None = None,
                    interpret: bool = False):
    """q: [B, Sq, Hq, D]; k/v: [B, Sk, Hkv, D] (GQA when Hq > Hkv).
    Returns [B, Sq, Hq, D]. block_q/block_k are upper-bound preferences;
    the actual blocks are tile-aligned divisors of Sq/Sk derived by
    derive_blocks (defaults: the tuned 512/1024). Raises ValueError for
    shapes with no valid tiling (the dispatcher falls back to the XLA
    path and logs)."""
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq % hkv != 0:
        raise ValueError(f"Hq={hq} not a multiple of Hkv={hkv}")
    block_q, block_k = derive_blocks(sq, sk, block_q, block_k)
    assert not (sq % block_q or sk % block_k or block_q % 8 or block_k % 128)
    rep = hq // hkv
    scale = d ** -0.5
    n_q = sq // block_q
    n_k = sk // block_k

    # [B, H, S, D] layout for clean blocking.
    qt = q.transpose(0, 2, 1, 3).reshape(b * hq, sq, d)
    kt = k.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)
    vt = v.transpose(0, 2, 1, 3).reshape(b * hkv, sk, d)

    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, n_k_blocks=n_k, diag_offset=sk - sq)

    def q_index(bi, hi, qi, ki):
        return (bi * hq + hi, qi, 0)

    def kv_index(bi, hi, qi, ki):
        return (bi * hkv + hi // rep, ki, 0)

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * hq, sq, d), q.dtype),
        grid=(b, hq, n_q, n_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # running max
            pltpu.VMEM((block_q, 128), jnp.float32),  # running denom
            pltpu.VMEM((block_q, d), jnp.float32),    # output accumulator
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(b, hq, sq, d).transpose(0, 2, 1, 3)
