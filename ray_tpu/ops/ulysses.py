"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head sharding.

The second long-context strategy alongside ring attention (SURVEY §2.4 —
the reference has neither; both are designed fresh here). Where ring
attention keeps the sequence sharded and rotates k/v around the mesh axis,
Ulysses RESHARDS for the attention op itself:

    in:  q/k/v sharded over sequence  [B, S/n, H, D]  (activations layout)
    all_to_all -> sharded over heads  [B, S, H/n, D]  (each device sees the
                                                       FULL sequence for a
                                                       1/n slice of heads)
    local attention (flash kernel / XLA — no cross-device math)
    all_to_all back -> sequence-sharded output [B, S/n, H, D]

Two all-to-alls of the activations per attention call, each moving
O(B.S.H.D / n) bytes per device over ICI — cheaper than ring's n-step
k/v rotation when heads divide evenly and S is large, but it caps the
sequence-parallel degree at the head count (ring has no such cap). Use
inside shard_map over the 'sp' (or any) mesh axis.
"""

from __future__ import annotations

import jax

from ray_tpu.ops import dot_product_attention


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True):
    """q: [B, S_local, H, D] sequence-sharded over `axis_name`; k/v the
    same layout (kv heads must also divide the axis size). Returns the
    sequence-sharded output [B, S_local, H, D]."""
    n = jax.lax.psum(1, axis_name)
    hq = q.shape[2]
    hkv = k.shape[2]
    if hq % n or hkv % n:
        raise ValueError(
            f"ulysses needs head counts divisible by the axis size "
            f"(q heads {hq}, kv heads {hkv}, axis {n})")

    def seq_to_heads(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]: split the head axis n ways,
        # all-to-all trades the sequence-shard axis for the head-shard
        # axis, then the gathered sequence chunks concatenate.
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def heads_to_seq(x):
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh = seq_to_heads(q)
    kh = seq_to_heads(k)
    vh = seq_to_heads(v)
    # Full-sequence attention over this device's head slice; causality is
    # exact because every device sees ALL positions.
    out = dot_product_attention(qh, kh, vh, causal=causal)
    return heads_to_seq(out)
