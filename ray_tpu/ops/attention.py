"""Attention ops: reference XLA implementation with a Pallas fast path.

The reference framework has no attention kernels of its own (it delegates to
torch/vLLM); this module is the TPU-native equivalent of that delegated
surface. `dot_product_attention` dispatches to the Pallas flash kernel on TPU
when shapes allow (ray_tpu/ops/flash_attention.py), else to a fused-softmax
XLA implementation that GSPMD can shard.
"""

from __future__ import annotations

import logging

import jax
import jax.numpy as jnp

logger = logging.getLogger(__name__)
# Warn once PER DISTINCT REASON (not once per process): a second, different
# shape rejection must not be silently swallowed by the first one's flag.
_warned_reasons: set[str] = set()


def dot_product_attention(q, k, v, *, causal: bool = True, use_pallas: bool | None = None):
    """q: [B, Sq, Hq, D], k/v: [B, Sk, Hkv, D] (GQA when Hq > Hkv).

    Returns [B, Sq, Hq, D]. Softmax in f32 regardless of input dtype
    (bf16-safe), output in the input dtype. Dispatches to the Pallas flash
    kernel on TPU; every fallback is LOGGED, never silent. The kernel's own
    ValueError is the single source of truth for shape support (no
    duplicated predicate to drift)."""
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"
    if use_pallas:
        from ray_tpu.ops.flash_attention import flash_attention

        try:
            return flash_attention(q, k, v, causal=causal)
        except ValueError as e:
            reason = str(e)
            if reason not in _warned_reasons:
                _warned_reasons.add(reason)
                logger.warning(
                    "attention falling back to the XLA path (%s); "
                    "O(Sq*Sk) memory", reason)
        except Exception as e:
            # Mosaic lowering limits, odd head dims, dtypes: loud safety net.
            logger.warning("flash attention kernel failed (%r); "
                           "falling back to XLA", e)
    return _xla_attention(q, k, v, causal=causal)


def _xla_attention(q, k, v, *, causal: bool):
    b, sq, hq, d = q.shape
    _, sk, hkv, _ = k.shape
    if hq != hkv:  # GQA: repeat kv heads
        rep = hq // hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)
