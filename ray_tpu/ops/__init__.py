"""Compute kernels (XLA + Pallas).

The hot ops live here so models call one stable surface while the
implementation graduates from reference jax (always correct, any backend)
to Pallas TPU kernels (ops/flash_attention.py) without touching model code.
"""

from ray_tpu.ops.attention import dot_product_attention


def ring_attention(*args, **kwargs):
    """Lazy alias for ray_tpu.ops.ring_attention.ring_attention."""
    from ray_tpu.ops.ring_attention import ring_attention as _ra

    return _ra(*args, **kwargs)


def ulysses_attention(*args, **kwargs):
    """Lazy alias for ray_tpu.ops.ulysses.ulysses_attention."""
    from ray_tpu.ops.ulysses import ulysses_attention as _ua

    return _ua(*args, **kwargs)


__all__ = ["dot_product_attention", "ring_attention", "ulysses_attention"]
