"""Compute kernels (XLA + Pallas).

The hot ops live here so models call one stable surface while the
implementation graduates from reference jax (always correct, any backend)
to Pallas TPU kernels (ops/flash_attention.py) without touching model code.
"""

from ray_tpu.ops.attention import dot_product_attention

__all__ = ["dot_product_attention", "ring_attention", "ulysses_attention"]


def __getattr__(name):
    # PEP 562 lazy exports. A def-style alias named `ring_attention` would
    # be CLOBBERED the first time the ray_tpu.ops.ring_attention submodule
    # imports (importlib setattrs the module object onto the package).
    if name == "ring_attention":
        from ray_tpu.ops.ring_attention import ring_attention as fn

        return fn
    if name == "ulysses_attention":
        from ray_tpu.ops.ulysses import ulysses_attention as fn

        return fn
    raise AttributeError(name)
