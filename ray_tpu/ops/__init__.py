"""Compute kernels (XLA + Pallas).

The hot ops live here so models call one stable surface while the
implementation graduates from reference jax (always correct, any backend)
to Pallas TPU kernels (ops/flash_attention.py) without touching model code.
"""

from ray_tpu.ops.attention import dot_product_attention

__all__ = ["dot_product_attention"]
