"""Compute kernels (XLA + Pallas).

The hot ops live here so models call one stable surface while the
implementation graduates from reference jax (always correct, any backend)
to Pallas TPU kernels (ops/flash_attention.py) without touching model code.
"""

from ray_tpu.ops.attention import dot_product_attention

__all__ = ["decode_attention", "dot_product_attention", "ring_attention",
           "ulysses_attention"]


def __getattr__(name):
    # PEP 562 lazy exports, PINNED into the package namespace on first
    # access: importing the ray_tpu.ops.ring_attention submodule setattrs
    # the module object onto the package, and without the pin a later
    # attribute lookup would resolve to that module instead of the
    # function (module __dict__ wins over __getattr__ only when the name
    # is absent — so put the function there).
    if name == "ring_attention":
        from ray_tpu.ops.ring_attention import ring_attention as fn
    elif name == "ulysses_attention":
        from ray_tpu.ops.ulysses import ulysses_attention as fn
    elif name == "decode_attention":
        from ray_tpu.ops.decode_attention import decode_attention as fn
    else:
        raise AttributeError(name)
    globals()[name] = fn
    return fn
