"""Datasources: pluggable block producers for the read APIs.

Parity target: reference python/ray/data/datasource/datasource.py (Datasource
/ ReadTask) + file_based_datasource.py (path expansion, per-file read tasks)
+ parquet/csv/json/text/binary/numpy datasources. Blocks are columnar dicts
of numpy arrays (or row lists), matching ray_tpu.data.block.BlockAccessor.
"""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Callable, Iterable, Optional

import numpy as np


class ReadTask:
    """A picklable unit of read work executed inside a remote task; calling
    it returns ONE block (reference ReadTask returns a block iterable; one
    block per task keeps the plan's block count == parallelism)."""

    def __init__(self, fn: Callable[[], Any], metadata: Optional[dict] = None):
        self._fn = fn
        self.metadata = metadata or {}

    def __call__(self):
        return self._fn()


class Datasource:
    """Base datasource (reference datasource.py:Datasource)."""

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def estimate_inmemory_data_size(self) -> Optional[int]:
        return None


def _expand_paths(paths) -> list[str]:
    """File path / dir / glob expansion (reference file_based_datasource
    path resolution, local scheme only — cloud storage is out of scope for
    the single-host object store; exchange spill goes through the
    `ray_tpu.storage` backend seam, not through datasource paths)."""
    if isinstance(paths, str):
        paths = [paths]
    out: list[str] = []
    for p in paths:
        p = os.path.expanduser(p)
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith((".", "_")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files found for {paths!r}")
    return out


class FileBasedDatasource(Datasource):
    """One read task per byte-sized file group; subclasses parse a single
    file. Blocks target RT_DATA_BLOCK_BYTES (reference file_based_
    datasource's target_max_block_size): many small files pack into one
    task, one oversized file splits into row-range slices — so the
    exchange downstream gets real parallelism either way, instead of one
    block per file."""

    #: Subclasses where a file's rows cannot be sliced (e.g. one row per
    #: whole file) set this False; oversized files then stay one block.
    _splittable = True

    def __init__(self, paths, **reader_kwargs):
        self._paths = _expand_paths(paths)
        self._kwargs = reader_kwargs

    def _read_file(self, path: str):
        raise NotImplementedError

    def _read_group(self, group: list):
        """group entries: a path (whole file) or a (path, j, m) triplet —
        slice j of m equal row ranges of one oversized file."""
        from ray_tpu.data.block import BlockAccessor, combine_blocks

        blocks = []
        for item in group:
            if isinstance(item, tuple):
                path, j, m = item
                acc = BlockAccessor.for_block(self._read_file(path))
                n = acc.num_rows()
                blocks.append(acc.slice((n * j) // m, (n * (j + 1)) // m))
            else:
                blocks.append(self._read_file(item))
        return blocks[0] if len(blocks) == 1 else combine_blocks(blocks)

    def estimate_inmemory_data_size(self) -> Optional[int]:
        try:
            return sum(os.path.getsize(p) for p in self._paths)
        except OSError:
            return None

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        from ray_tpu._private.rtconfig import CONFIG

        try:
            sizes = [os.path.getsize(p) for p in self._paths]
        except OSError:
            sizes = [0] * len(self._paths)
        total = sum(sizes)
        if total <= 0:
            # No size information: fall back to count-based contiguous
            # chunks, one group per unit of parallelism.
            n = max(1, min(parallelism, len(self._paths)))
            base, extra = divmod(len(self._paths), n)
            groups, start = [], 0
            for i in range(n):
                count = base + (1 if i < extra else 0)
                if count:
                    groups.append(self._paths[start:start + count])
                    start += count
        else:
            # Target bytes per block: RT_DATA_BLOCK_BYTES capped so the
            # requested parallelism is still reachable when the data is
            # small. Contiguous packing keeps block order == file order.
            target = max(1, min(max(1, CONFIG.data_block_bytes),
                                total // max(1, parallelism) or total))
            groups = []
            cur: list = []
            cur_bytes = 0
            for path, size in zip(self._paths, sizes):
                if self._splittable and size > target:
                    if cur:
                        groups.append(cur)
                        cur, cur_bytes = [], 0
                    m = -(-size // target)  # ceil: slices per big file
                    groups.extend([(path, j, m)] for j in range(m))
                    continue
                if cur and cur_bytes + size > target:
                    groups.append(cur)
                    cur, cur_bytes = [], 0
                cur.append(path)
                cur_bytes += size
            if cur:
                groups.append(cur)
        return [ReadTask(_BoundGroupRead(self, g), {"paths": g}) for g in groups]


class _BoundGroupRead:
    """Picklable (datasource, group) closure for a read task."""

    def __init__(self, ds: FileBasedDatasource, group: list):
        self.ds = ds
        self.group = group

    def __call__(self):
        return self.ds._read_group(self.group)


def _table_to_block(table) -> dict:
    """Arrow table -> columnar numpy block."""
    return {name: table.column(name).to_numpy(zero_copy_only=False)
            for name in table.column_names}


class ParquetDatasource(FileBasedDatasource):
    def __init__(self, paths, columns: Optional[list[str]] = None, **kw):
        super().__init__(paths, **kw)
        self._columns = columns

    def _read_file(self, path: str):
        import pyarrow.parquet as pq

        return _table_to_block(pq.read_table(path, columns=self._columns))


class CSVDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        import pyarrow.csv as pacsv

        return _table_to_block(pacsv.read_csv(path, **self._kwargs))


class JSONDatasource(FileBasedDatasource):
    """JSON-lines (and pyarrow-supported JSON) files."""

    def _read_file(self, path: str):
        import pyarrow.json as pajson

        return _table_to_block(pajson.read_json(path, **self._kwargs))


class TextDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        with open(path, "r", encoding=self._kwargs.get("encoding", "utf-8")) as f:
            lines = f.read().splitlines()
        if self._kwargs.get("drop_empty_lines", True):
            lines = [l for l in lines if l]
        return {"text": np.asarray(lines, dtype=object)}


class BinaryDatasource(FileBasedDatasource):
    _splittable = False  # one row per whole file: no row ranges to cut

    def _read_group(self, group: list[str]):
        data, paths = [], []
        for p in group:
            with open(p, "rb") as f:
                data.append(f.read())
            paths.append(p)
        block = {"bytes": np.asarray(data, dtype=object)}
        if self._kwargs.get("include_paths", False):
            block["path"] = np.asarray(paths, dtype=object)
        return block

    def _read_file(self, path: str):  # pragma: no cover - _read_group overrides
        raise NotImplementedError


class NumpyDatasource(FileBasedDatasource):
    def _read_file(self, path: str):
        arr = np.load(path, allow_pickle=self._kwargs.get("allow_pickle", False))
        return {"data": arr}


class RangeDatasource(Datasource):
    """range / range_tensor (reference read_api.range: 'id' column)."""

    def __init__(self, n: int, tensor_shape: Optional[tuple] = None,
                 column: str = "id"):
        self.n = n
        self.tensor_shape = tensor_shape
        self.column = column

    def estimate_inmemory_data_size(self) -> int:
        per = 8
        if self.tensor_shape:
            per = 8 * int(np.prod(self.tensor_shape))
        return self.n * per

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = max(1, min(parallelism, self.n) if self.n else 1)
        per = self.n // n
        extra = self.n % n
        tasks, start = [], 0
        for i in range(n):
            count = per + (1 if i < extra else 0)
            if count == 0:
                continue
            tasks.append(ReadTask(
                _RangeRead(start, count, self.tensor_shape, self.column),
                {"num_rows": count}))
            start += count
        return tasks


class _RangeRead:
    def __init__(self, start, count, tensor_shape, column):
        self.start, self.count = start, count
        self.tensor_shape, self.column = tensor_shape, column

    def __call__(self):
        ids = np.arange(self.start, self.start + self.count)
        if self.tensor_shape is None:
            return {self.column: ids}
        reps = int(np.prod(self.tensor_shape))
        data = np.repeat(ids, reps).reshape((self.count, *self.tensor_shape))
        return {"data": data}


class ItemsDatasource(Datasource):
    """from_items: local python objects, split across blocks."""

    def __init__(self, items: list):
        self.items = list(items)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = max(1, min(parallelism, len(self.items)) if self.items else 1)
        per = len(self.items) // n
        extra = len(self.items) % n
        tasks, start = [], 0
        for i in range(n):
            count = per + (1 if i < extra else 0)
            if count == 0:
                continue
            chunk = self.items[start:start + count]
            tasks.append(ReadTask(lambda c=chunk: c, {"num_rows": count}))
            start += count
        return tasks
