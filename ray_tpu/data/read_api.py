"""Public Dataset constructors.

Parity target: reference python/ray/data/read_api.py (from_items:110,
range:196, read_parquet:771, read_csv:1372, read_json:1178, read_text,
read_binary_files, read_numpy, from_numpy, from_pandas, from_arrow,
read_datasource:446). Reads are lazy: each datasource read task runs inside
a remote task when the plan executes, so file parsing happens on the
cluster, not the driver.
"""

from __future__ import annotations

from typing import Any, Optional, Union

import numpy as np

from ray_tpu.data._internal import executor as ex
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
)

# Default read parallelism when -1 is passed (reference auto-detects from
# cluster size + file sizes; a fixed modest default keeps plans predictable).
DEFAULT_PARALLELISM = 8


def read_datasource(datasource: Datasource, *, parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = DEFAULT_PARALLELISM
    tasks = datasource.get_read_tasks(parallelism)
    return Dataset([ex.ReadSource(tasks)])


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001 - reference name
    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def range_tensor(n: int, *, shape: tuple = (1,), parallelism: int = -1) -> Dataset:
    return read_datasource(RangeDatasource(n, tensor_shape=tuple(shape)),
                           parallelism=parallelism)


def from_numpy(arrays: Union[np.ndarray, list]) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = [arrays]
    blocks = [{"data": np.asarray(a)} for a in arrays]
    return Dataset([ex.Read(lambda b=blocks: b, len(blocks))])


def from_pandas(dfs) -> Dataset:
    if not isinstance(dfs, list):
        dfs = [dfs]
    blocks = [{c: df[c].to_numpy() for c in df.columns} for df in dfs]
    return Dataset([ex.Read(lambda b=blocks: b, len(blocks))])


def from_arrow(tables) -> Dataset:
    from ray_tpu.data.datasource import _table_to_block

    if not isinstance(tables, list):
        tables = [tables]
    blocks = [_table_to_block(t) for t in tables]
    return Dataset([ex.Read(lambda b=blocks: b, len(blocks))])


def read_parquet(paths, *, columns: Optional[list] = None,
                 parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(ParquetDatasource(paths, columns=columns, **kw),
                           parallelism=parallelism)


def read_csv(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(CSVDatasource(paths, **kw), parallelism=parallelism)


def read_json(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(JSONDatasource(paths, **kw), parallelism=parallelism)


def read_text(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(TextDatasource(paths, **kw), parallelism=parallelism)


def read_binary_files(paths, *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    return read_datasource(BinaryDatasource(paths, include_paths=include_paths),
                           parallelism=parallelism)


def read_numpy(paths, *, parallelism: int = -1, **kw) -> Dataset:
    return read_datasource(NumpyDatasource(paths, **kw), parallelism=parallelism)
