"""Blocks: the unit of data the executor moves through the object store.

Parity target: reference python/ray/data/block.py (Block/BlockAccessor).
A block is either a list of rows (dicts / scalars) or a column dict of numpy
arrays ("batch layout"). BlockAccessor normalizes between the two.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np


def _column(values) -> np.ndarray:
    """Build a numpy column from row values. bytes/str rows must get
    object dtype: numpy's fixed-width S/U dtypes treat trailing NULs as
    padding and silently strip them on element access."""
    if isinstance(values, np.ndarray):
        return values
    vals = values if isinstance(values, list) else list(values)
    if vals and isinstance(vals[0], (bytes, bytearray, str)):
        return np.asarray(vals, dtype=object)
    return np.asarray(vals)


class BlockAccessor:
    def __init__(self, block):
        self.block = block

    @staticmethod
    def for_block(block) -> "BlockAccessor":
        return BlockAccessor(block)

    def is_columnar(self) -> bool:
        return isinstance(self.block, dict)

    def num_rows(self) -> int:
        if self.is_columnar():
            if not self.block:
                return 0
            return len(next(iter(self.block.values())))
        return len(self.block)

    def iter_rows(self) -> Iterable[Any]:
        if self.is_columnar():
            cols = list(self.block.keys())
            for i in range(self.num_rows()):
                yield {c: self.block[c][i] for c in cols}
        else:
            yield from self.block

    def to_rows(self) -> list:
        return list(self.iter_rows())

    def to_batch(self) -> dict:
        """Column dict of numpy arrays."""
        if self.is_columnar():
            return {k: _column(v) for k, v in self.block.items()}
        if not self.block:
            return {}
        first = self.block[0]
        if isinstance(first, dict):
            cols = list(first.keys())
            return {c: _column([r[c] for r in self.block]) for c in cols}
        return {"item": _column(self.block)}

    def slice(self, start: int, end: int):
        if self.is_columnar():
            return {k: v[start:end] for k, v in self.block.items()}
        return self.block[start:end]

    def schema(self):
        if self.is_columnar():
            return {k: np.asarray(v).dtype for k, v in self.block.items()}
        if self.block and isinstance(self.block[0], dict):
            return {k: type(v).__name__ for k, v in self.block[0].items()}
        return {"item": type(self.block[0]).__name__} if self.block else None


def combine_blocks(blocks: list) -> Any:
    """Merge same-layout blocks into one."""
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([_column(b[k]) for b in blocks]) for k in keys}
    out = []
    for b in blocks:
        out.extend(b)
    return out
