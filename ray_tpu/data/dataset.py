"""Dataset: lazy logical plan over distributed blocks.

Parity target: reference python/ray/data/dataset.py:158 (Dataset — lazy
logical plan -> physical operators), iterator APIs
(iterator.py DataIterator), streaming_split feeding trainers
(reference _internal/execution/streaming_executor.py + train integration
session.py:1114 get_dataset_shard).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import _internal
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data._internal import executor as ex


class Dataset:
    def __init__(self, plan: list):
        self._plan = plan
        self._cached_refs: Optional[list] = None

    # ----------------------------------------------------------- transforms
    def _extend(self, op) -> "Dataset":
        return Dataset(self._plan + [op])

    def map(self, fn: Callable) -> "Dataset":
        return self._extend(ex.MapRows(fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._extend(ex.FlatMap(fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._extend(ex.Filter(fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None) -> "Dataset":
        """Batch transform. A callable CLASS (or concurrency=N) runs on an
        actor pool — __init__ once per actor, the batch-inference pattern
        (reference dataset.py map_batches + ActorPoolMapOperator).
        batch_format: "numpy" (dict of arrays, the TPU-feed format) or
        "pandas" (DataFrame in, DataFrame out)."""
        if batch_format not in ("numpy", "default", "pandas"):
            raise ValueError(f"unsupported batch_format {batch_format!r}")
        return self._extend(ex.MapBatches(
            fn, batch_size, batch_format=batch_format, concurrency=concurrency,
            fn_constructor_args=fn_constructor_args,
            fn_constructor_kwargs=fn_constructor_kwargs))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def _add(batch, _name=name, _fn=fn):
            batch[_name] = _fn(batch)
            return batch

        return self._extend(ex.MapBatches(_add, None))

    def drop_columns(self, cols: list[str]) -> "Dataset":
        drop = set(cols)
        return self._extend(ex.MapBatches(
            lambda b: {k: v for k, v in b.items() if k not in drop}, None))

    def select_columns(self, cols: list[str]) -> "Dataset":
        keep = list(cols)
        return self._extend(ex.MapBatches(
            lambda b: {k: b[k] for k in keep}, None))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._extend(ex.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._extend(ex.RandomShuffle(seed))

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        return self._extend(ex.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._extend(ex.Limit(n))

    def union(self, other: "Dataset") -> "Dataset":
        return self._extend(ex.Union(other._plan))

    # ---------------------------------------------------------- execution
    def materialize(self) -> "Dataset":
        """Execute the plan now; the result holds resolved block refs
        (reference Dataset.materialize -> MaterializedDataset)."""
        refs = self._block_refs()
        out = Dataset([ex.Read(lambda: refs, len(refs))])
        out._cached_refs = refs
        return out

    def _block_refs(self) -> list:
        if self._cached_refs is None:
            self._cached_refs = ex.execute(self._plan)
        return self._cached_refs

    # --------------------------------------------------------- consumption
    def take(self, n: int = 20) -> list:
        out = []
        for ref in self._block_refs():
            block = ray_tpu.get(ref, timeout=600)
            for row in BlockAccessor.for_block(block).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> list:
        return self.take(n=1 << 62)

    def count(self) -> int:
        total = 0
        for ref in self._block_refs():
            total += BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).num_rows()
        return total

    def num_blocks(self) -> int:
        return len(self._block_refs())

    def schema(self):
        refs = self._block_refs()
        if not refs:
            return None
        return BlockAccessor.for_block(ray_tpu.get(refs[0], timeout=600)).schema()

    def iter_rows(self) -> Iterable[Any]:
        for ref in self._block_refs():
            yield from BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterable[dict]:
        """Stream column-dict batches (reference iter_batches). An
        unexecuted plan streams through _internal.streaming: a trailing
        all-to-all op is consumed block-by-block as its pipelined exchange
        produces reduce outputs, never materialized driver-side. The block
        refs are cached only after a full consumption."""
        if self._cached_refs is not None:
            it = DataIterator(self._cached_refs)
            yield from it.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format)
            return
        from ray_tpu.data._internal import streaming

        def _cache(refs):
            if self._cached_refs is None:
                self._cached_refs = refs

        yield from streaming.iter_batches(
            self._plan, batch_size=batch_size, batch_format=batch_format,
            on_complete=_cache)

    def to_numpy(self, column: Optional[str] = None):
        batches = list(self.iter_batches(batch_size=1 << 30))
        from ray_tpu.data.block import combine_blocks

        merged = combine_blocks(batches) if batches else {}
        if column is not None:
            return merged[column]
        if set(merged.keys()) == {"item"}:
            return merged["item"]
        return merged

    def streaming_split(self, n: int, *, equal: bool = True) -> list["DataIterator"]:
        """Split into n iterators for n training workers (reference
        Dataset.streaming_split feeding get_dataset_shard). equal=True
        (the training default) gives every shard EXACTLY total//n rows,
        dropping the remainder — unequal shards hang lockstep allreduce
        training."""
        refs = self._block_refs()
        if equal:
            return [DataIterator(s) for s in ex._equal_split(refs, n)]
        if len(refs) < n:
            refs = ex._repartition(refs, n)
        shards: list[list] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [DataIterator(s) for s in shards]

    def split(self, n: int) -> list["Dataset"]:
        """General-purpose split: keeps EVERY row (unlike streaming_split's
        training default, which equalizes by dropping the remainder)."""
        return [Dataset([ex.Read(lambda s=s: list(s._refs), len(s._refs))])
                for s in self.streaming_split(n, equal=False)]

    # ------------------------------------------------------------- writes
    def _write(self, path: str, fmt: str, ext: str) -> list[str]:
        """One output file per block, written by remote tasks (reference
        write_parquet/_csv/_json -> per-block write tasks)."""
        import os

        os.makedirs(path, exist_ok=True)
        refs = self._block_refs()
        outs = [_write_block.remote(ref, os.path.join(
            path, f"part-{i:05d}.{ext}"), fmt) for i, ref in enumerate(refs)]
        return ray_tpu.get(outs, timeout=600)

    def write_parquet(self, path: str) -> list[str]:
        return self._write(path, "parquet", "parquet")

    def write_csv(self, path: str) -> list[str]:
        return self._write(path, "csv", "csv")

    def write_json(self, path: str) -> list[str]:
        return self._write(path, "json", "json")

    # --------------------------------------------------------- aggregates
    def _agg(self, on: Optional[str], op: str, combine):
        """Per-block partial aggregates computed in remote tasks; only the
        scalars come back to the driver."""
        refs = self._block_refs()
        if on is None:
            # Resolve the column ONCE from the schema so every block
            # aggregates the same column; require it to be unambiguous.
            schema = self.schema() or {}
            cols = list(schema)
            if len(cols) != 1:
                raise ValueError(
                    f"dataset has columns {cols}; pass on=<column> to aggregate")
            on = cols[0]
        parts = [p for p in ray_tpu.get(
            [_partial_agg.remote(r, on, op) for r in refs], timeout=600)
            if p is not None]
        return combine(parts) if parts else None

    def sum(self, on: Optional[str] = None):
        return self._agg(on, "sum", sum)

    def min(self, on: Optional[str] = None):
        return self._agg(on, "min", min)

    def max(self, on: Optional[str] = None):
        return self._agg(on, "max", max)

    def mean(self, on: Optional[str] = None):
        tot = self._agg(on, "sum_count",
                        lambda ps: tuple(map(sum, zip(*ps))))
        if tot is None:
            return None
        s, n = tot
        return s / n if n else None

    def groupby(self, key) -> "GroupedData":
        return GroupedData(self, key)

    def __repr__(self):
        names = [type(op).__name__ for op in self._plan]
        return f"Dataset(plan={' -> '.join(names)})"


@ray_tpu.remote
def _write_block(block, path: str, fmt: str) -> str:
    import pyarrow as pa

    batch = BlockAccessor.for_block(block).to_batch()
    table = pa.table({k: pa.array(v) for k, v in batch.items()})
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(table, path)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(table, path)
    elif fmt == "json":
        import json

        with open(path, "w") as f:
            for row in BlockAccessor.for_block(block).iter_rows():
                f.write(json.dumps(
                    {k: (v.item() if hasattr(v, "item") else v)
                     for k, v in row.items()} if isinstance(row, dict)
                    else row) + "\n")
    else:
        raise ValueError(f"unknown write format {fmt}")
    return path


@ray_tpu.remote
def _partial_agg(block, on: str, op: str):
    """One block's partial aggregate (scalar or (sum, count) pair)."""
    batch = BlockAccessor.for_block(block).to_batch()
    if not batch:
        return None
    if on not in batch:
        raise KeyError(f"block is missing aggregation column {on!r} "
                       f"(has {list(batch)})")
    v = batch[on]
    if not len(v):
        return None
    if op == "sum":
        return np.sum(v)
    if op == "min":
        return np.min(v)
    if op == "max":
        return np.max(v)
    if op == "sum_count":
        return (np.sum(v), len(v))
    raise ValueError(f"unknown aggregate {op}")


@ray_tpu.remote
def _partial_group(block, key, on):
    """Map-side partial aggregation: key -> (rows, values, sum, min, max).
    `values` counts rows that actually carry the aggregation column — mean
    must divide by it, not by the row count."""
    acc = BlockAccessor.for_block(block)
    out: dict = {}
    kf = key if callable(key) else (
        lambda r: r[key] if isinstance(r, dict) else r)
    for row in acc.iter_rows():
        k = kf(row)
        v = row.get(on) if (on is not None and isinstance(row, dict)) else None
        c, vc, s, mn, mx = out.get(k, (0, 0, 0.0, None, None))
        c += 1
        if v is not None:
            vc += 1
            s += v
            mn = v if mn is None else min(mn, v)
            mx = v if mx is None else max(mx, v)
        out[k] = (c, vc, s, mn, mx)
    return out


class GroupedData:
    """groupby aggregations via map-side partial agg + driver combine
    (reference grouped_data.py; the reference shuffles — at this scale a
    tree-combine of partial states is the same result cheaper)."""

    def __init__(self, ds: Dataset, key):
        self._ds = ds
        self._key = key
        # Output rows need a string column name; a callable key has none.
        self._key_col = key if isinstance(key, str) else "key"

    def _combined(self, on: Optional[str]) -> dict:
        parts = ray_tpu.get(
            [_partial_group.remote(r, self._key, on)
             for r in self._ds._block_refs()], timeout=600)
        merged: dict = {}
        for part in parts:
            for k, (c, vc, s, mn, mx) in part.items():
                C, VC, S, MN, MX = merged.get(k, (0, 0, 0.0, None, None))
                merged[k] = (
                    C + c, VC + vc, S + s,
                    mn if MN is None else (MN if mn is None else min(MN, mn)),
                    mx if MX is None else (MX if mx is None else max(MX, mx)))
        return merged

    def _to_dataset(self, rows: list) -> Dataset:
        return Dataset([ex.Read(lambda b=[rows]: b, 1)])

    def count(self) -> Dataset:
        rows = [{self._key_col: k, "count()": c}
                for k, (c, *_rest) in sorted(self._combined(None).items())]
        return self._to_dataset(rows)

    def sum(self, on: str) -> Dataset:
        rows = [{self._key_col: k, f"sum({on})": s}
                for k, (_c, _vc, s, _mn, _mx) in sorted(self._combined(on).items())]
        return self._to_dataset(rows)

    def mean(self, on: str) -> Dataset:
        rows = [{self._key_col: k, f"mean({on})": s / vc}
                for k, (_c, vc, s, _mn, _mx) in sorted(self._combined(on).items())
                if vc]
        return self._to_dataset(rows)

    def min(self, on: str) -> Dataset:
        rows = [{self._key_col: k, f"min({on})": mn}
                for k, (_c, _vc, _s, mn, _mx) in sorted(self._combined(on).items())]
        return self._to_dataset(rows)

    def max(self, on: str) -> Dataset:
        rows = [{self._key_col: k, f"max({on})": mx}
                for k, (_c, _vc, _s, _mn, mx) in sorted(self._combined(on).items())]
        return self._to_dataset(rows)


class DataIterator:
    """Per-consumer block iterator (reference python/ray/data/iterator.py
    DataIterator). Picklable: holds object refs, so it can be shipped to a
    training worker and consumed there."""

    def __init__(self, refs: list):
        self._refs = list(refs)

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterable[dict]:
        carry: Optional[dict] = None
        from ray_tpu.data.block import combine_blocks

        for ref in self._refs:
            block = ray_tpu.get(ref, timeout=600)
            batch = BlockAccessor.for_block(block).to_batch()
            if carry:
                batch = combine_blocks([carry, batch])
                carry = None
            n = len(next(iter(batch.values()))) if batch else 0
            s = 0
            while n - s >= batch_size:
                yield {k: v[s:s + batch_size] for k, v in batch.items()}
                s += batch_size
            if s < n:
                carry = {k: v[s:] for k, v in batch.items()}
        if carry and not drop_last:
            yield carry

    def iter_rows(self) -> Iterable[Any]:
        for ref in self._refs:
            yield from BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).iter_rows()

    def materialize(self) -> "Dataset":
        return Dataset([ex.Read(lambda: list(self._refs), len(self._refs))])

    def __reduce__(self):
        return (DataIterator, (self._refs,))
