"""Dataset: lazy logical plan over distributed blocks.

Parity target: reference python/ray/data/dataset.py:158 (Dataset — lazy
logical plan -> physical operators), iterator APIs
(iterator.py DataIterator), streaming_split feeding trainers
(reference _internal/execution/streaming_executor.py + train integration
session.py:1114 get_dataset_shard).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional

import numpy as np

import ray_tpu
from ray_tpu.data import _internal
from ray_tpu.data.block import BlockAccessor
from ray_tpu.data._internal import executor as ex


class Dataset:
    def __init__(self, plan: list):
        self._plan = plan
        self._cached_refs: Optional[list] = None

    # ----------------------------------------------------------- transforms
    def _extend(self, op) -> "Dataset":
        return Dataset(self._plan + [op])

    def map(self, fn: Callable) -> "Dataset":
        return self._extend(ex.MapRows(fn))

    def flat_map(self, fn: Callable) -> "Dataset":
        return self._extend(ex.FlatMap(fn))

    def filter(self, fn: Callable) -> "Dataset":
        return self._extend(ex.Filter(fn))

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None) -> "Dataset":
        return self._extend(ex.MapBatches(fn, batch_size))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._extend(ex.Repartition(num_blocks))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._extend(ex.RandomShuffle(seed))

    def sort(self, key=None, descending: bool = False) -> "Dataset":
        return self._extend(ex.Sort(key, descending))

    def limit(self, n: int) -> "Dataset":
        return self._extend(ex.Limit(n))

    def union(self, other: "Dataset") -> "Dataset":
        return self._extend(ex.Union(other._plan))

    # ---------------------------------------------------------- execution
    def materialize(self) -> "Dataset":
        """Execute the plan now; the result holds resolved block refs
        (reference Dataset.materialize -> MaterializedDataset)."""
        refs = self._block_refs()
        out = Dataset([ex.Read(lambda: refs, len(refs))])
        out._cached_refs = refs
        return out

    def _block_refs(self) -> list:
        if self._cached_refs is None:
            self._cached_refs = ex.execute(self._plan)
        return self._cached_refs

    # --------------------------------------------------------- consumption
    def take(self, n: int = 20) -> list:
        out = []
        for ref in self._block_refs():
            block = ray_tpu.get(ref, timeout=600)
            for row in BlockAccessor.for_block(block).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> list:
        return self.take(n=1 << 62)

    def count(self) -> int:
        total = 0
        for ref in self._block_refs():
            total += BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).num_rows()
        return total

    def num_blocks(self) -> int:
        return len(self._block_refs())

    def schema(self):
        refs = self._block_refs()
        if not refs:
            return None
        return BlockAccessor.for_block(ray_tpu.get(refs[0], timeout=600)).schema()

    def iter_rows(self) -> Iterable[Any]:
        for ref in self._block_refs():
            yield from BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy") -> Iterable[dict]:
        """Stream column-dict batches (reference iter_batches)."""
        it = DataIterator(self._block_refs())
        yield from it.iter_batches(batch_size=batch_size, batch_format=batch_format)

    def to_numpy(self, column: Optional[str] = None):
        batches = list(self.iter_batches(batch_size=1 << 30))
        from ray_tpu.data.block import combine_blocks

        merged = combine_blocks(batches) if batches else {}
        if column is not None:
            return merged[column]
        if set(merged.keys()) == {"item"}:
            return merged["item"]
        return merged

    def streaming_split(self, n: int, *, equal: bool = True) -> list["DataIterator"]:
        """Split into n iterators for n training workers (reference
        Dataset.streaming_split feeding get_dataset_shard)."""
        refs = self._block_refs()
        if len(refs) < n:
            refs = ex._repartition(refs, n)
        shards: list[list] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [DataIterator(s) for s in shards]

    def split(self, n: int) -> list["Dataset"]:
        return [Dataset([ex.Read(lambda s=s: list(s._refs), len(s._refs))])
                for s in self.streaming_split(n)]

    def __repr__(self):
        names = [type(op).__name__ for op in self._plan]
        return f"Dataset(plan={' -> '.join(names)})"


class DataIterator:
    """Per-consumer block iterator (reference python/ray/data/iterator.py
    DataIterator). Picklable: holds object refs, so it can be shipped to a
    training worker and consumed there."""

    def __init__(self, refs: list):
        self._refs = list(refs)

    def iter_batches(self, *, batch_size: int = 256, batch_format: str = "numpy",
                     drop_last: bool = False) -> Iterable[dict]:
        carry: Optional[dict] = None
        from ray_tpu.data.block import combine_blocks

        for ref in self._refs:
            block = ray_tpu.get(ref, timeout=600)
            batch = BlockAccessor.for_block(block).to_batch()
            if carry:
                batch = combine_blocks([carry, batch])
                carry = None
            n = len(next(iter(batch.values()))) if batch else 0
            s = 0
            while n - s >= batch_size:
                yield {k: v[s:s + batch_size] for k, v in batch.items()}
                s += batch_size
            if s < n:
                carry = {k: v[s:] for k, v in batch.items()}
        if carry and not drop_last:
            yield carry

    def iter_rows(self) -> Iterable[Any]:
        for ref in self._refs:
            yield from BlockAccessor.for_block(ray_tpu.get(ref, timeout=600)).iter_rows()

    def materialize(self) -> "Dataset":
        return Dataset([ex.Read(lambda: list(self._refs), len(self._refs))])

    def __reduce__(self):
        return (DataIterator, (self._refs,))
