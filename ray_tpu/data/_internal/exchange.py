"""Pipelined distributed exchange: the map/reduce core of the data plane.

Parity target: reference python/ray/data/_internal/planner/exchange/ (the
sort/shuffle task specs) executed the *streaming* way — reference
streaming_executor.py keeps every operator's work bounded and in flight
concurrently instead of materializing stage boundaries.

The exchange here replaces the v0 barrier (`_exchange_maps`: ALL map
tasks complete before any reduce submits) with a pipelined loop:

- map tasks run under the per-operator in-flight budget
  (RT_DATA_MAX_INFLIGHT_BLOCKS) with the store-backpressure brake, and
  each one's partition shards become available the moment it finishes
  (multi-return: one owned object per partition, straight into node shm
  via the task-return `put_serialized` one-copy path — same-host shards
  never round-trip through pickled RPC payloads);
- the reduce side starts merging as soon as a partition's first inputs
  land: whenever a partition has RT_DATA_REDUCE_FANIN shards pending,
  a consolidation task merges them into one object (bounded fan-in,
  applied recursively — no reduce ever takes an unbounded arg list);
- under memory pressure consolidated shards spill through the storage
  plane (spill.py) and restore transparently at the final reduce;
- finalized partition refs are YIELDED in partition order as their
  reduce tasks submit, so a downstream `iter_batches()` consumer starts
  before the exchange drains (streaming.py rides this).

Determinism: every shard is tagged with its producing map index and every
merge orders entries by that tag before combining, so the output is
byte-identical regardless of completion order, pipelining mode
(RT_DATA_PIPELINED_EXCHANGE=0 barrier A/B leg), or mid-exchange retries
(chaos: a SIGKILLed map/reduce worker's shards re-execute through the
PR 6 dedup plane and land in the same slots).
"""

from __future__ import annotations

import heapq
import pickle
import random
import threading
import time
import uuid
from typing import Callable, Iterator, Optional

import ray_tpu
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.data._internal import spill as _spill
from ray_tpu.data.block import BlockAccessor, combine_blocks

# ------------------------------------------------------------------ stats
# Process-local exchange telemetry: the driver loop bumps the in-flight /
# stall / ordering / spill fields (spills from the resolved consolidation
# metas, so each spill is counted in exactly one process); reduce tasks
# bump restored_bytes in their own worker process. telemetry.WorkerSampler
# and util.metrics export whatever the local process accumulated
# (sys.modules-gated, like the device-store and llm series).
_STATS_LOCK = threading.Lock()
_STATS = {
    "exchanges": 0,            # completed exchanges (driver)
    "maps_done": 0,            # map tasks completed (driver)
    "reduces_submitted": 0,    # consolidation + final reduce tasks (driver)
    "blocks_inflight": 0,      # gauge: block tasks in flight right now
    "max_inflight": 0,         # high-water mark of the above
    "bp_stalls": 0,            # submit-loop pauses on store backpressure
    "spilled_bytes": 0,        # payload bytes written to the spill backend
    "spilled_parts": 0,        # shards spilled
    "restored_bytes": 0,       # payload bytes restored on consume
    "reduce_before_last_map": 0,  # 1 once a reduce submitted with maps live
    "stream_max_ahead": 0,     # streaming consumption: max unconsumed blocks
}


def exchange_stats() -> dict:
    with _STATS_LOCK:
        return dict(_STATS)


def reset_exchange_stats() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[key] += n


def _gauge_inflight(n: int) -> None:
    with _STATS_LOCK:
        _STATS["blocks_inflight"] = n
        if n > _STATS["max_inflight"]:
            _STATS["max_inflight"] = n


def note_stream_ahead(n: int) -> None:
    """Streaming consumers report their unconsumed-block high-water mark
    here (pinned by the in-flight-budget test)."""
    with _STATS_LOCK:
        if n > _STATS["stream_max_ahead"]:
            _STATS["stream_max_ahead"] = n


# ------------------------------------------------------------------ helpers
def _key_fn(key):
    return key if callable(key) else (
        lambda r, k=key: r[k] if isinstance(r, dict) else r)


def inflight_budget() -> int:
    return max(1, CONFIG.data_max_inflight_blocks)


def _flatten_parts(parts) -> list:
    """Normalize reduce inputs to a flat list of (map_idx, rows) entries.
    A part is a tagged shard tuple (one map task's output for this
    partition), a list of entries (a consolidation task's output), or a
    SpilledPart marker (restored through the storage plane)."""
    entries: list = []
    for part in parts:
        if isinstance(part, _spill.SpilledPart):
            restored = _spill.restore(part)
            _bump("restored_bytes", part.nbytes)
            entries.extend(restored)
        elif isinstance(part, tuple):
            entries.append(part)
        else:
            entries.extend(part)
    return entries


# ------------------------------------------------------------ remote tasks
@ray_tpu.remote
def _consolidate(spec: Optional[dict], *parts):
    """Incremental reduce-side merge of one partition's pending shards
    (bounded fan-in). Two returns: a tiny meta dict the driver may inspect
    without touching the payload, and the consolidated payload itself —
    either the entry list (staying in shm via the one-copy return path) or
    a SpilledPart marker when the spill policy triggers."""
    entries = _flatten_parts(parts)
    meta = {"nbytes": 0, "spilled": False}
    payload = entries
    if spec is not None:
        blob = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        meta["nbytes"] = len(blob)
        cap = spec.get("cap") or 0
        if spec.get("force") or (cap and len(blob) > cap):
            payload = _spill.spill_bytes(blob, spec["uri"], spec["partition"])
            # No _bump here: the meta is the single source of truth for
            # spill accounting — the driver resolves it after the drain.
            # A worker-side bump would double-count through the per-process
            # metrics drain once the driver bumps its own stats.
            meta["spilled"] = True
    return [meta, payload]


@ray_tpu.remote
def _finalize_partition(op: str, arg, *parts):
    """Final reduce of one partition. Entries are ordered by producing map
    index first, so output is independent of arrival order and merge
    grouping (see module docstring on determinism)."""
    entries = _flatten_parts(parts)
    entries.sort(key=lambda e: e[0])
    if op == "sort":
        key, descending = arg
        return list(heapq.merge(*[e[1] for e in entries],
                                key=_key_fn(key), reverse=descending))
    if op == "concat":
        # Format-preserving merge (repartition): shards are block slices,
        # not row lists; empty shards (a map had no rows for this
        # partition) would poison columnar concatenation.
        blocks = [e[1] for e in entries
                  if BlockAccessor.for_block(e[1]).num_rows()]
        return combine_blocks(blocks)
    rows: list = []
    for _idx, part_rows in entries:
        rows.extend(part_rows)
    if op == "shuffle":
        random.Random(arg).shuffle(rows)
    return rows


# ------------------------------------------------------------- driver loop
def exchange_partitions(refs: list, *, op: str, k: int,
                        map_submit: Callable[[int, object], list],
                        finalize_arg=None) -> Iterator:
    """Run one all-to-all exchange; yields each partition's final block
    ref in partition order, submitting reduces as inputs land.

    map_submit(i, ref) submits map task i with num_returns=k and returns
    its per-partition shard refs; each shard must be a (map_idx, rows)
    tuple. op is "shuffle" / "sort" / "concat" (+ finalize_arg: the
    partition-seed base for shuffle, (key, descending) for sort).
    """
    from ray_tpu.data._internal.executor import _store_backpressured

    if not refs:
        return
    pipelined = CONFIG.data_pipelined_exchange
    fanin = max(2, CONFIG.data_reduce_fanin)
    budget = inflight_budget()
    mem_cap = CONFIG.data_mem_cap_bytes
    spill_uri = _spill.spill_root()
    ex_id = uuid.uuid4().hex[:8]
    spill_seq = 0
    t0 = time.monotonic()

    # parts[p]: pending reduce inputs for partition p (tagged shard refs
    # and consolidation payload refs). meta_refs: consolidation metas,
    # resolved once at the end for the spill accounting.
    parts: list[list] = [[] for _ in range(k)]
    meta_refs: list = []
    pending: dict = {}  # first shard ref -> full shard ref list
    submitted = 0
    maps_done = 0

    def _spill_spec(p: int) -> Optional[dict]:
        nonlocal spill_seq
        force = _store_backpressured()
        if not force and not mem_cap:
            return None  # no policy armed: skip the serialize-for-size pass
        spill_seq += 1
        return {
            "uri": f"{spill_uri}/ex-{ex_id}/p{p}-{spill_seq}.bin",
            "cap": mem_cap, "partition": p, "force": force,
        }

    def _consolidate_p(p: int) -> None:
        spec = _spill_spec(p)
        out = _consolidate.options(num_returns=2).remote(spec, *parts[p])
        meta_refs.append(out[0])
        parts[p] = [out[1]]
        _bump("reduces_submitted")
        if pending:  # reduce-side merge submitted with maps still in flight
            with _STATS_LOCK:
                _STATS["reduce_before_last_map"] = 1

    while submitted < len(refs) or pending:
        stalled = False
        while submitted < len(refs) and len(pending) < budget:
            if pending and _store_backpressured():
                # The brake only engages with work already in flight:
                # progress is always possible even when the store starts
                # above the mark.
                stalled = True
                break
            shard_refs = map_submit(submitted, refs[submitted])
            if not isinstance(shard_refs, list):
                shard_refs = [shard_refs]
            pending[shard_refs[0]] = shard_refs
            submitted += 1
            _gauge_inflight(len(pending))
        if stalled:
            _bump("bp_stalls")
        if pending:
            done, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=10)
            for d in done:
                shard_refs = pending.pop(d, None)
                if shard_refs is None:
                    continue
                maps_done += 1
                _bump("maps_done")
                for p in range(k):
                    parts[p].append(shard_refs[p if k > 1 else 0])
            _gauge_inflight(len(pending))
            if pipelined and pending:
                # Reduce-side merging starts the moment a partition's
                # pending shards reach the fan-in bound — while maps are
                # still running (the no-barrier core of this module).
                for p in range(k):
                    if len(parts[p]) >= fanin:
                        _consolidate_p(p)

    for p in range(k):
        # Keep the final reduce's fan-in bounded too: a tail of shards
        # that never hit the bound mid-flight consolidates here first.
        while pipelined and len(parts[p]) > fanin:
            _consolidate_p(p)
        arg = finalize_arg(p) if callable(finalize_arg) else finalize_arg
        out = _finalize_partition.remote(op, arg, *parts[p])
        parts[p] = []
        _bump("reduces_submitted")
        yield out

    # Exchange accounting: resolve the (tiny) consolidation metas, emit
    # ONE lifecycle event per exchange — never per block.
    spilled_bytes = spilled_parts = 0
    try:
        for meta in ray_tpu.get(meta_refs, timeout=600):
            if meta.get("spilled"):
                spilled_bytes += meta["nbytes"]
                spilled_parts += 1
    except Exception:
        pass  # a failed consolidation surfaces via its payload consumer
    if spilled_parts:
        _bump("spilled_bytes", spilled_bytes)
        _bump("spilled_parts", spilled_parts)
    _bump("exchanges")
    try:
        from ray_tpu._private.events import emit_event

        if spilled_parts:
            emit_event(
                "data_spill",
                f"exchange {op} spilled {spilled_parts} shard(s)",
                attrs={"op": op, "bytes": spilled_bytes,
                       "parts": spilled_parts,
                       "scheme": spill_uri.split("://", 1)[0]})
        emit_event(
            "data_exchange",
            f"{op} exchange: {len(refs)} maps -> {k} partitions",
            attrs={"op": op, "maps": len(refs), "partitions": k,
                   "pipelined": bool(pipelined),
                   "spilled_bytes": spilled_bytes,
                   "elapsed_s": round(time.monotonic() - t0, 3)})
    except Exception:
        pass


def run_exchange(refs: list, **kw) -> list:
    """Materializing wrapper: run the exchange to completion, return the
    per-partition block refs."""
    return list(exchange_partitions(refs, **kw))
