"""Streaming execution of a logical plan over the task pool.

Parity target: reference python/ray/data/_internal/execution/
streaming_executor.py:52 (pull-based streaming over an operator DAG with
bounded in-flight work) + operators/map_operator.py:64 (task-based map) +
logical/optimizers.py (operator fusion).

Design: logical ops are fused into per-block transform chains
(reference's MapOperator fusion), executed as remote tasks with a bounded
in-flight window so a long dataset streams instead of materializing; blocks
live in the object store between stages. All-to-all ops (repartition,
random_shuffle, sort) ride the pipelined map/reduce exchange in
exchange.py (reduce-side merging overlaps the map wave — no stage
barrier); streaming consumption lives in streaming.py. This module owns
the logical plan, fusion, and the per-block map path.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Optional

import ray_tpu
from ray_tpu.data._internal import exchange as _ex
from ray_tpu.data.block import BlockAccessor, combine_blocks
#: Pause new block submissions while cluster shm usage is above this
#: fraction of capacity (consumers/spill catch up; submissions resume).
STORE_BACKPRESSURE_FRACTION = 0.75
_BP_POLL_S = 0.2

_bp_cache = {"t": 0.0, "hit": False}


def _store_backpressured() -> bool:
    """Cluster object-store usage above the high-water mark? Cached for
    _BP_POLL_S so the hot submit loop costs one controller round trip per
    poll interval, not per block."""
    now = time.monotonic()
    if now - _bp_cache["t"] < _BP_POLL_S:
        return _bp_cache["hit"]
    _bp_cache["t"] = now
    try:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        rep = w.io.run(w.controller.call("object_store_stats"), timeout=10)
        cap = rep.get("capacity") or 1
        _bp_cache["hit"] = rep.get("shm_bytes", 0) > \
            STORE_BACKPRESSURE_FRACTION * cap
    except Exception:
        _bp_cache["hit"] = False
    return _bp_cache["hit"]


# ------------------------------------------------------------ logical plan
class LogicalOp:
    name = "op"


class Read(LogicalOp):
    name = "Read"

    def __init__(self, blocks_fn: Callable[[], list], num_blocks: int):
        self.blocks_fn = blocks_fn  # () -> list of block payloads or refs
        self.num_blocks = num_blocks


class ReadSource(LogicalOp):
    """Lazy datasource read: each ReadTask runs in a remote task when the
    plan executes (reference read_api.read_datasource -> ReadTask tasks in
    the streaming executor's first operator)."""

    name = "ReadSource"

    def __init__(self, tasks: list):
        self.tasks = tasks  # list[ray_tpu.data.datasource.ReadTask]
        self.num_blocks = len(tasks)


class MapRows(LogicalOp):
    name = "Map"

    def __init__(self, fn):
        self.fn = fn


class FlatMap(LogicalOp):
    name = "FlatMap"

    def __init__(self, fn):
        self.fn = fn


class Filter(LogicalOp):
    name = "Filter"

    def __init__(self, fn):
        self.fn = fn


class MapBatches(LogicalOp):
    name = "MapBatches"

    def __init__(self, fn, batch_size: Optional[int],
                 batch_format: str = "numpy",
                 concurrency: Optional[int] = None,
                 fn_constructor_args: tuple = (),
                 fn_constructor_kwargs: Optional[dict] = None):
        self.fn = fn
        self.batch_size = batch_size
        self.batch_format = batch_format
        # concurrency (or a class fn) switches execution to an actor pool
        # (reference operators/map_operator.py:64 ActorPoolMapOperator).
        self.concurrency = concurrency
        self.fn_constructor_args = fn_constructor_args
        self.fn_constructor_kwargs = fn_constructor_kwargs or {}

    @property
    def needs_actors(self) -> bool:
        return self.concurrency is not None or isinstance(self.fn, type)


class Repartition(LogicalOp):
    name = "Repartition"

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks


class RandomShuffle(LogicalOp):
    name = "RandomShuffle"

    def __init__(self, seed: Optional[int]):
        self.seed = seed


class Sort(LogicalOp):
    name = "Sort"

    def __init__(self, key, descending: bool):
        self.key = key
        self.descending = descending


class Limit(LogicalOp):
    name = "Limit"

    def __init__(self, n: int):
        self.n = n


class Union(LogicalOp):
    name = "Union"

    def __init__(self, other_plan: list):
        self.other_plan = other_plan


# ------------------------------------------------------------- transforms
def _apply_chain(block, chain):
    """Run a fused chain of row/batch transforms over one block in-task."""
    for kind, fn, arg in chain:
        acc = BlockAccessor.for_block(block)
        if kind == "map":
            block = [fn(r) for r in acc.iter_rows()]
        elif kind == "flat_map":
            out = []
            for r in acc.iter_rows():
                out.extend(fn(r))
            block = out
        elif kind == "filter":
            block = [r for r in acc.iter_rows() if fn(r)]
        elif kind == "map_batches":
            bs, fmt = arg if isinstance(arg, tuple) else (arg, "numpy")
            bs = bs or acc.num_rows() or 1
            pieces = []
            n = acc.num_rows()
            for s in range(0, n, bs):
                batch = (acc.to_batch() if (s == 0 and bs >= n)
                         else BlockAccessor.for_block(
                             acc.slice(s, min(s + bs, n))).to_batch())
                if fmt == "pandas":
                    import pandas as pd

                    df = fn(pd.DataFrame(batch))
                    out = {c: df[c].to_numpy() for c in df.columns}
                else:
                    out = fn(batch)
                pieces.append(out)
            block = combine_blocks(pieces) if pieces else block
    return block


@ray_tpu.remote
def _transform_block(block, chain):
    return _apply_chain(block, chain)


@ray_tpu.remote
def _exec_read_task(task, chain):
    """Run a datasource ReadTask (and any fused downstream per-block chain)
    inside a worker: file parsing happens on the cluster, not the driver."""
    block = task()
    return _apply_chain(block, chain) if chain else block


@ray_tpu.remote
class _MapBatchesActor:
    """Actor-pool map worker (reference ActorPoolMapOperator's _MapWorker):
    a callable-class fn is constructed ONCE per actor — the pattern for
    batch inference, where __init__ loads model weights."""

    def __init__(self, fn, args, kwargs):
        self.fn = fn(*args, **kwargs) if isinstance(fn, type) else fn

    def apply(self, block, batch_size, batch_format="numpy"):
        return _apply_chain(
            block, [("map_batches", self.fn, (batch_size, batch_format))])


@ray_tpu.remote
def _split_block(block, sizes):
    acc = BlockAccessor.for_block(block)
    out, off = [], 0
    for s in sizes:
        out.append(acc.slice(off, off + s))
        off += s
    return out if len(out) > 1 else out[0]


def _key_fn(key):
    return key if callable(key) else (
        lambda r, k=key: r[k] if isinstance(r, dict) else r)


@ray_tpu.remote
def _sort_block_local(block, key, descending):
    rows = BlockAccessor.for_block(block).to_rows()
    return sorted(rows, key=_key_fn(key), reverse=descending)


# ---- distributed exchange map tasks (reference planner/exchange/
# sort_task_spec.py + shuffle_task_spec.py map sides; the reduce side —
# consolidation + finalize — lives in exchange.py). Every shard is tagged
# (map_idx, payload) so exchange merges are arrival-order independent; the
# driver touches only sampled keys and refs, never rows. ------------------
@ray_tpu.remote
def _sample_block_keys(block, key, n_samples):
    """Uniform key sample of one block (reference SortTaskSpec.sample)."""
    rows = BlockAccessor.for_block(block).to_rows()
    if not rows:
        return []
    kf = _key_fn(key)
    rng = random.Random(0xC0FFEE ^ len(rows))
    picks = rows if len(rows) <= n_samples else rng.sample(rows, n_samples)
    return [kf(r) for r in picks]


@ray_tpu.remote
def _sort_map(block, map_idx, key, descending, boundaries):
    """Map side of the sort exchange: bucket rows by ASCENDING range
    boundaries, each bucket sorted in final order; one return per range
    (reference sort_task_spec.map)."""
    import bisect

    rows = BlockAccessor.for_block(block).to_rows()
    kf = _key_fn(key)
    buckets: list[list] = [[] for _ in range(len(boundaries) + 1)]
    for r in rows:
        buckets[bisect.bisect_right(boundaries, kf(r))].append(r)
    for b in buckets:
        b.sort(key=kf, reverse=descending)
    if descending:
        buckets.reverse()  # partition 0 holds the LARGEST keys
    tagged = [(map_idx, b) for b in buckets]
    return tagged if len(tagged) > 1 else tagged[0]


@ray_tpu.remote
def _shuffle_map(block, map_idx, k, seed):
    """Map side of the shuffle exchange: permute this block's rows and deal
    them into k sub-blocks (reference shuffle_task_spec.map)."""
    rows = BlockAccessor.for_block(block).to_rows()
    rng = random.Random(seed)
    rng.shuffle(rows)
    per = len(rows) // k
    extra = len(rows) % k
    parts, off = [], 0
    for i in range(k):
        take = per + (1 if i < extra else 0)
        parts.append((map_idx, rows[off:off + take]))
        off += take
    return parts if k > 1 else parts[0]


@ray_tpu.remote
def _repart_map(block, map_idx, k, parts):
    """Map side of the repartition exchange: cut this block's driver-planned
    row ranges into format-preserving slices, one return per output
    partition (empty slice where none of this block lands)."""
    acc = BlockAccessor.for_block(block)
    out: list = [(map_idx, [])] * k
    off = 0
    for pi, take in parts:
        out[pi] = (map_idx, acc.slice(off, off + take))
        off += take
    return out if k > 1 else out[0]


# -------------------------------------------------------------- execution
def _fuse(plan: list) -> list:
    """Fuse consecutive per-row/batch ops into chains (reference fusion
    rule, logical/optimizers.py). Actor-pool map_batches stages break the
    chain: they execute on a dedicated actor pool."""
    fused: list = []
    chain: list = []
    for op in plan:
        if isinstance(op, MapRows):
            chain.append(("map", op.fn, None))
        elif isinstance(op, FlatMap):
            chain.append(("flat_map", op.fn, None))
        elif isinstance(op, Filter):
            chain.append(("filter", op.fn, None))
        elif isinstance(op, MapBatches) and not op.needs_actors:
            chain.append(("map_batches", op.fn, (op.batch_size, op.batch_format)))
        else:
            if chain:
                fused.append(("chain", chain))
                chain = []
            fused.append(("op", op))
    if chain:
        fused.append(("chain", chain))
    return fused


def _windowed_submit(items: list, submit) -> list:
    """Submit one task per item with a bounded in-flight window (streaming
    — reference streaming_executor's bounded operator concurrency). The
    window is the per-operator block budget (RT_DATA_MAX_INFLIGHT_BLOCKS)
    plus the store-backpressure brake (reference backpressure_policy/:
    ConcurrencyCapBackpressurePolicy + the object-store-memory policy)."""
    budget = _ex.inflight_budget()
    out = [None] * len(items)
    in_flight: dict = {}
    i = 0
    while i < len(items) or in_flight:
        while (i < len(items) and len(in_flight) < budget
               and not (in_flight and _store_backpressured())):
            # The brake only engages with work already in flight: progress
            # is always possible even when the store starts above the mark.
            out[i] = submit(items[i])
            # Multi-return submits (exchange map tasks) track their first
            # ref: all returns of one task resolve together.
            in_flight[out[i][0] if isinstance(out[i], list) else out[i]] = i
            i += 1
        if in_flight:
            done, _ = ray_tpu.wait(list(in_flight), num_returns=1, timeout=10)
            for d in done:
                in_flight.pop(d, None)
    return out


def _windowed_map(refs: list, chain) -> list:
    return _windowed_submit(refs, lambda r: _transform_block.remote(r, chain))


def _actor_pool_map(refs: list, op: "MapBatches") -> list:
    """Run a map_batches stage on a pool of actors: least-loaded dispatch
    with a small per-actor pipeline (reference ActorPoolMapOperator +
    _ActorPool in operators/actor_pool_map_operator.py)."""
    n = max(1, min(op.concurrency or 1, len(refs) or 1))
    actors = [_MapBatchesActor.remote(op.fn, tuple(op.fn_constructor_args),
                                      dict(op.fn_constructor_kwargs))
              for _ in range(n)]
    try:
        out = [None] * len(refs)
        pending: dict = {}  # result ref -> actor index
        load = [0] * n
        i = 0
        while i < len(refs) or pending:
            while i < len(refs) and min(load) < 2:
                ai = load.index(min(load))
                r = actors[ai].apply.remote(refs[i], op.batch_size,
                                            op.batch_format)
                out[i] = r
                pending[r] = ai
                load[ai] += 1
                i += 1
            if pending:
                done, _ = ray_tpu.wait(list(pending), num_returns=1, timeout=10)
                for d in done:
                    load[pending.pop(d)] -= 1
        # Results are resolved (inline or node-shm with the agent as holder),
        # so the pool can be torn down before downstream consumption.
        return out
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _equal_split(refs: list, n: int) -> list[list]:
    """Split blocks into n shards with IDENTICAL row counts (total//n each,
    remainder dropped) — lockstep allreduce training hangs on unequal shards
    (reference streaming_split(equal=True) -> equalize splits)."""
    sizes = _block_sizes(refs)
    total = sum(sizes)
    per = total // n
    shards: list[list] = [[] for _ in range(n)]
    if per == 0:
        return shards
    si, need = 0, per
    for ref, size in zip(refs, sizes):
        # Plan this block's cuts: (target shard | None to drop, row count).
        parts: list[tuple[Optional[int], int]] = []
        off = 0
        while off < size:
            if si >= n:
                parts.append((None, size - off))  # remainder: dropped
                break
            take = min(size - off, need)
            parts.append((si, take))
            off += take
            need -= take
            if need == 0:
                si += 1
                need = per
        if len(parts) == 1 and parts[0][0] is not None:
            shards[parts[0][0]].append(ref)
            continue
        if all(s is None for s, _t in parts):
            continue  # block is entirely dropped remainder: no task needed
        # Cut in a remote task with one return per piece: payloads never
        # visit the driver (streaming_split feeds trainers with datasets
        # larger than driver memory).
        prefs = _split_block.options(num_returns=len(parts)).remote(
            ref, [t for _s, t in parts])
        if not isinstance(prefs, list):
            prefs = [prefs]
        for (sidx, _t), pref in zip(parts, prefs):
            if sidx is not None:
                shards[sidx].append(pref)
    return shards


def execute(plan: list) -> list:
    """Run the logical plan, returning block refs."""
    assert plan and isinstance(plan[0], (Read, ReadSource))
    fused = _fuse(plan[1:])
    if isinstance(plan[0], ReadSource):
        # Fuse the first per-block chain straight into the read tasks: one
        # remote task parses AND transforms each block (reference
        # read->map fusion).
        read_chain = None
        if fused and fused[0][0] == "chain":
            read_chain = fused.pop(0)[1]
        refs = _windowed_submit(
            plan[0].tasks,
            lambda t: _exec_read_task.remote(t, read_chain))
    else:
        refs = [b if isinstance(b, ray_tpu.ObjectRef) else ray_tpu.put(b)
                for b in plan[0].blocks_fn()]
    for kind, item in fused:
        if kind == "chain":
            refs = _windowed_map(refs, item)
            continue
        op = item
        if isinstance(op, Repartition):
            refs = _repartition(refs, op.num_blocks)
        elif isinstance(op, RandomShuffle):
            refs = _random_shuffle(refs, op.seed)
        elif isinstance(op, Sort):
            refs = _global_sort(refs, op.key, op.descending)
        elif isinstance(op, Limit):
            refs = _limit(refs, op.n)
        elif isinstance(op, Union):
            refs = refs + execute(op.other_plan)
        elif isinstance(op, MapBatches):  # actor-pool stage
            refs = _actor_pool_map(refs, op)
        else:
            raise ValueError(f"unknown op {op.name}")
    return refs


@ray_tpu.remote
def _count_rows(block) -> int:
    return BlockAccessor.for_block(block).num_rows()


def _block_sizes(refs: list) -> list[int]:
    """Row counts WITHOUT pulling block payloads to the driver."""
    return ray_tpu.get([_count_rows.remote(r) for r in refs], timeout=600)


def _repartition(refs: list, k: int) -> list:
    return list(_repartition_stream(refs, k))


def _repartition_stream(refs: list, k: int):
    """Repartition as a pipelined exchange: the driver plans row-range
    assignments from block COUNTS, map tasks cut format-preserving slices,
    the exchange's reduce side concatenates each output partition
    (reference planner/exchange/). Rows never visit the driver; returns an
    iterator of partition refs (streaming.py consumes it lazily)."""
    if not refs:
        return iter(())
    sizes = _block_sizes(refs)
    total = sum(sizes)
    target = [total // k + (1 if i < total % k else 0) for i in range(k)]
    # Assign row ranges to output partitions.
    splits_per_block = []
    t_i, t_left = 0, target[0] if target else 0
    for s in sizes:
        parts = []
        left = s
        while left > 0:
            take = min(left, t_left) if t_left else left
            parts.append((t_i, take))
            left -= take
            t_left -= take
            while t_left == 0 and t_i < k - 1:
                t_i += 1
                t_left = target[t_i]
        splits_per_block.append(parts)
    stream = _ex.exchange_partitions(
        refs, op="concat", k=k,
        map_submit=lambda i, r: _repart_map.options(num_returns=k).remote(
            r, i, k, splits_per_block[i]))
    # Empty output partitions (fewer rows than k) are dropped, matching
    # Dataset.num_blocks() semantics for tiny datasets.
    return (b for b, t in zip(stream, target) if t)


def _random_shuffle(refs: list, seed) -> list:
    return list(_random_shuffle_stream(refs, seed))


def _random_shuffle_stream(refs: list, seed):
    """Distributed shuffle exchange (reference shuffle_task_spec.py): map
    tasks permute + deal each block into k sub-blocks, the exchange's
    reduce side merges one sub-block per map and re-permutes. Rows never
    visit the driver."""
    if not refs:
        return iter(())
    k = len(refs)
    base = seed if seed is not None else random.randrange(1 << 30)
    return _ex.exchange_partitions(
        refs, op="shuffle", k=k,
        map_submit=lambda i, r: _shuffle_map.options(num_returns=k).remote(
            r, i, k, base ^ (0x9E3779B9 * (i + 1))),
        finalize_arg=lambda p: base ^ (0x85EBCA6B * (p + 1)))


def _global_sort(refs: list, key, descending) -> list:
    return list(_global_sort_stream(refs, key, descending))


def _global_sort_stream(refs: list, key, descending):
    """Distributed sort exchange (reference sort_task_spec.py): sample keys
    -> compute k-1 range boundaries -> map tasks range-partition + locally
    sort -> the exchange's reduce side heap-merges each range. The driver
    sees sampled KEYS only, never rows."""
    if not refs:
        return iter(())
    k = len(refs)
    if k == 1:
        return iter([_sort_block_local.remote(refs[0], key, descending)])
    # 1. sample (driver holds ~20 keys per block, not rows)
    samples_per_block = 20
    key_samples: list = []
    for sref in _windowed_submit(
            refs, lambda r: _sample_block_keys.remote(
                r, key, samples_per_block)):
        key_samples.extend(ray_tpu.get(sref, timeout=600))
    key_samples.sort()
    if not key_samples:
        return iter(refs)
    # 2. boundaries: k-1 ascending quantile cut points
    boundaries = [key_samples[min(len(key_samples) - 1,
                                  (len(key_samples) * (i + 1)) // k)]
                  for i in range(k - 1)]
    # 3+4. map (range-partition + local sort) feeding the pipelined merge;
    # partition order already matches `descending` — _sort_map reverses
    # bucket order for descending.
    return _ex.exchange_partitions(
        refs, op="sort", k=k,
        map_submit=lambda i, r: _sort_map.options(num_returns=k).remote(
            r, i, key, descending, boundaries),
        finalize_arg=(key, descending))


def _limit(refs: list, n: int) -> list:
    out, have = [], 0
    for ref in refs:
        if have >= n:
            break
        block = ray_tpu.get(ref, timeout=600)
        acc = BlockAccessor.for_block(block)
        r = acc.num_rows()
        if have + r <= n:
            out.append(ref)
            have += r
        else:
            out.append(ray_tpu.put(acc.slice(0, n - have)))
            have = n
    return out
