"""Exchange spill/restore through the PR 8 storage plane.

Under memory pressure the pipelined exchange (exchange.py) consolidates
partition shards and writes them through `ray_tpu.storage` instead of
keeping them in shm: any registered backend works (`local://`, `mem://`,
`sim://` — the last one fault-injectable, which is how the chaos tests
sever the spill path). A spilled shard travels as a tiny `SpilledPart`
marker; the reduce task that consumes it restores the payload
transparently, retrying `StorageTransientError` with bounded backoff and
raising an attributed `DataSpillError` when the backend stays gone —
never a hang.

Spill policy (driver + task cooperate, both deterministic):

- the driver FORCES a spill on any consolidation submitted while the
  cluster store sits above `STORE_BACKPRESSURE_FRACTION`;
- the task spills when `RT_DATA_MEM_CAP_BYTES` is set and the
  consolidated payload alone exceeds it (the forced-low-cap test knob).

A restored shard deletes its own backing file (best effort): the spill
dir self-cleans as the exchange drains.
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Optional

from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.exceptions import DataSpillError

#: Transient-failure retry schedule for one storage op: bounded, so a
#: severed backend surfaces in ~1.5s instead of hanging the reduce.
_RETRIES = 5
_RETRY_BASE_S = 0.05


class SpilledPart:
    """Marker for a shard that lives in the storage plane, not shm.
    Picklable and tiny: this is what rides the object store in place of
    the payload."""

    __slots__ = ("uri", "nbytes", "partition")

    def __init__(self, uri: str, nbytes: int, partition: int):
        self.uri = uri
        self.nbytes = nbytes
        self.partition = partition

    def __reduce__(self):
        return (SpilledPart, (self.uri, self.nbytes, self.partition))

    def __repr__(self):
        return f"SpilledPart({self.uri}, {self.nbytes}B, p{self.partition})"


def spill_root() -> str:
    """Storage URI exchange shards spill under (RT_DATA_SPILL_URI, default
    local://<session_dir>/data_spill)."""
    uri = CONFIG.data_spill_uri
    if uri:
        return uri
    return "local://" + os.path.join(CONFIG.session_dir, "data_spill")


def _retrying(op: str, uri: str, partition: Optional[int], fn):
    """Run one storage op with the bounded transient-retry schedule."""
    from ray_tpu.storage.backend import StorageTransientError

    last: Exception | None = None
    for attempt in range(_RETRIES):
        try:
            return fn()
        except StorageTransientError as e:
            last = e
            time.sleep(_RETRY_BASE_S * (2 ** attempt))
    raise DataSpillError(
        f"exchange {op} failed after {_RETRIES} transient retries: {uri} "
        f"(partition {partition}): {last}",
        uri=uri, partition=partition, op=op) from last


def spill_bytes(blob: bytes, uri: str, partition: int) -> SpilledPart:
    """Write one consolidated shard payload; returns the marker that rides
    the object store in its place."""
    from ray_tpu import storage

    _retrying("spill", uri, partition, lambda: storage.put(uri, blob))
    return SpilledPart(uri, len(blob), partition)


def spill_entries(entries: list, uri: str, partition: int) -> SpilledPart:
    return spill_bytes(
        pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL),
        uri, partition)


def restore(part: SpilledPart) -> list:
    """Read a spilled shard back (bounded retries, attributed error) and
    best-effort delete its backing file — the spill dir self-cleans."""
    from ray_tpu import storage

    blob = _retrying("restore", part.uri, part.partition,
                     lambda: storage.get_bytes(part.uri))
    entries = pickle.loads(blob)
    try:
        storage.delete(part.uri)
    except Exception:
        pass  # injected fault or already gone; the payload is what matters
    return entries
