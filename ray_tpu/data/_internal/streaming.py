"""Streaming consumption: iter_batches without driver materialization.

Parity target: reference python/ray/data/iterator.py (iter_batches) over
_internal/execution/streaming_executor.py output — the consumer reads
batches while upstream operators are still producing blocks.

`iter_batches(plan)` drives the plan's trailing all-to-all op (if any)
through the pipelined exchange LAZILY: exchange.exchange_partitions is a
generator, so each block the consumer pulls advances the exchange by at
most one final-reduce submission. Combined with the bounded look-ahead
window here (the same RT_DATA_MAX_INFLIGHT_BLOCKS budget the exchange
uses for its map wave), the driver never holds more than `budget`
unconsumed block refs — an ingest-to-train loop over a dataset larger
than driver memory stays flat (the budget-pin test reads the high-water
mark from exchange_stats()["stream_max_ahead"]).
"""

from __future__ import annotations

from collections import deque
from typing import Iterable, Iterator, Optional

import ray_tpu
from ray_tpu.data._internal import exchange as _ex
from ray_tpu.data.block import BlockAccessor, combine_blocks


def stream_blocks(plan: list) -> Iterator:
    """Yield the plan's output block refs, pipelining a trailing
    all-to-all op instead of materializing its full output ref list."""
    from ray_tpu.data._internal import executor as ex

    last = plan[-1] if len(plan) > 1 else None
    if isinstance(last, (ex.Repartition, ex.RandomShuffle, ex.Sort)):
        refs = ex.execute(plan[:-1])
        if isinstance(last, ex.RandomShuffle):
            yield from ex._random_shuffle_stream(refs, last.seed)
        elif isinstance(last, ex.Sort):
            yield from ex._global_sort_stream(refs, last.key, last.descending)
        else:
            yield from ex._repartition_stream(refs, last.num_blocks)
        return
    yield from ex.execute(plan)


def iter_batches(plan: list, *, batch_size: int = 256,
                 batch_format: str = "numpy",
                 on_complete=None) -> Iterable[dict]:
    """Stream column-dict batches from a logical plan with a bounded
    block look-ahead. `on_complete(refs)` fires only when the stream is
    fully drained — Dataset uses it to cache the block refs so a second
    consumption doesn't re-execute the plan."""
    budget = _ex.inflight_budget()
    src = stream_blocks(plan)
    buf: deque = deque()
    seen: list = []
    exhausted = False
    carry: Optional[dict] = None
    while True:
        while not exhausted and len(buf) < budget:
            try:
                ref = next(src)
            except StopIteration:
                exhausted = True
                break
            buf.append(ref)
            seen.append(ref)
            _ex.note_stream_ahead(len(buf))
        if not buf:
            break
        block = ray_tpu.get(buf.popleft(), timeout=600)
        batch = BlockAccessor.for_block(block).to_batch()
        if carry:
            batch = combine_blocks([carry, batch])
            carry = None
        n = len(next(iter(batch.values()))) if batch else 0
        s = 0
        while n - s >= batch_size:
            yield {k: v[s:s + batch_size] for k, v in batch.items()}
            s += batch_size
        if s < n:
            carry = {k: v[s:] for k, v in batch.items()}
    if carry:
        yield carry
    if on_complete is not None:
        on_complete(seen)
