"""ray_tpu.data: distributed datasets over the object store.

Parity target: reference python/ray/data/__init__.py — Dataset +
constructors (read_api.py) + datasources. Lazy logical plans execute as
remote tasks with bounded in-flight streaming; blocks are columnar numpy
dicts (TPU-friendly host format: feeds jnp.asarray without a copy for
numeric dtypes).
"""

from ray_tpu.data.block import BlockAccessor
from ray_tpu.data.dataset import DataIterator, Dataset, GroupedData
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.read_api import (
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,  # noqa: A004 - reference name
    range_tensor,
    read_binary_files,
    read_csv,
    read_datasource,
    read_json,
    read_numpy,
    read_parquet,
    read_text,
)

__all__ = [
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "ReadTask",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "range_tensor",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_json",
    "read_numpy",
    "read_parquet",
    "read_text",
]
