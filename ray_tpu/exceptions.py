"""Exception hierarchy for the ray_tpu runtime.

Parity target: ray/exceptions.py in the reference (RayError, RayTaskError,
RayActorError, ObjectLostError, GetTimeoutError, ...). Re-designed minimal set
for the TPU-native runtime.
"""

from __future__ import annotations


class RayTpuError(Exception):
    """Base class for all ray_tpu runtime errors."""


class TaskError(RayTpuError):
    """Wraps an exception raised inside a remote task/actor method.

    Mirrors the reference's RayTaskError (python/ray/exceptions.py): the remote
    traceback is captured as text and re-raised at `get()` on the caller.
    """

    def __init__(self, function_name: str, traceback_str: str, cause: Exception | None = None):
        self.function_name = function_name
        self.traceback_str = traceback_str
        self.cause = cause
        super().__init__(
            f"Remote task {function_name!r} failed:\n{traceback_str}"
        )


class OutOfMemoryError(RayTpuError):
    """The node memory monitor killed a worker to relieve memory pressure
    (reference ray.exceptions.OutOfMemoryError, memory_monitor.h +
    worker_killing_policy.h)."""


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ActorError(RayTpuError):
    """Base for actor-related failures."""


class ActorDiedError(ActorError):
    """The actor is dead (crashed, killed, or out of restarts).

    Parity: reference RayActorError / ActorDiedError.
    """


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class ObjectLostError(RayTpuError):
    """Object's value was lost (all copies gone) and could not be reconstructed."""


class ObjectReconstructionError(ObjectLostError):
    """Lineage reconstruction failed (e.g. non-retryable parent task)."""


class OwnerDiedError(ObjectLostError):
    """The owner process of this object died, so the object is unrecoverable."""


class TaskCancelledError(RayTpuError):
    """The task was cancelled via ray_tpu.cancel() (reference TaskCancelledError;
    cancel RPC core_worker.proto:492)."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """`get()` timed out. The message carries the producing task's status
    (queued/running, node, seconds since its last progress beacon) when the
    runtime can attribute it — the first question a stalled-get user asks."""


class TaskTimeoutError(RayTpuError, TimeoutError):
    """A task exceeded its per-attempt execution deadline
    (`@remote(timeout_s=...)`). Enforced worker-side; treated as a system
    failure, so the attempt retries under `max_retries` before this
    surfaces at `get()`."""


class CollectiveTimeoutError(RayTpuError, TimeoutError):
    """A host-tier collective op (util.collective) exceeded its per-op
    deadline (RT_COLLECTIVE_TIMEOUT_S) — typically a ring wedged on a sick
    peer. The message names the op, group, rank, and the peer the op was
    waiting on."""


def _rebuild_back_pressure_error(message, deployment, reason, queued,
                                 retry_after_s):
    return BackPressureError(message, deployment=deployment, reason=reason,
                             queued=queued, retry_after_s=retry_after_s)


class BackPressureError(RayTpuError):
    """A serve request was shed by admission control instead of queued
    unboundedly (README "Overload & admission control").

    Raised from the router when a deployment's bounded queue is full
    (`reason="queue_full"`), when a queued request could not be assigned
    before its `queue_deadline_s` (`reason="deadline"`), from the HTTP
    proxy's per-route token bucket (`reason="rate_limit"`), or replica-side
    when a request lands on a replica already at `max_ongoing_requests`
    (`reason="replica_busy"` — a cross-router race; routers retry these
    against other replicas). `retry_after_s` is the shed's retry hint — the
    proxy surfaces it as an HTTP `Retry-After` header on the 429/503.
    """

    def __init__(self, message: str, *, deployment: str | None = None,
                 reason: str = "queue_full", queued: int = 0,
                 retry_after_s: float = 1.0):
        self.deployment = deployment
        self.reason = reason
        self.queued = queued
        self.retry_after_s = retry_after_s
        super().__init__(message)

    def __reduce__(self):
        return (_rebuild_back_pressure_error,
                (str(self), self.deployment, self.reason, self.queued,
                 self.retry_after_s))


def _rebuild_dag_stage_error(message, stage, node, invocation, traceback_str):
    return DagStageError(message, stage=stage, node=node,
                         invocation=invocation, traceback_str=traceback_str)


class DagStageError(RayTpuError):
    """A compiled-DAG stage failed or died (README "Compiled graphs").

    Raised on `DagRef.get()` for the invocation(s) the failure covers:
    either the stage's user code raised (the remote traceback is carried in
    `traceback_str`), or the stage process/actor died mid-steady-state (the
    compiled driver's liveness monitor attributes the death). `stage` names
    the failed stage, `node` the node it ran on when known, `invocation`
    the in-flight sequence number the error was delivered for.
    """

    def __init__(self, message: str, *, stage: str | None = None,
                 node: str | None = None, invocation: int | None = None,
                 traceback_str: str | None = None):
        self.stage = stage
        self.node = node
        self.invocation = invocation
        self.traceback_str = traceback_str
        super().__init__(message)

    def __reduce__(self):
        return (_rebuild_dag_stage_error,
                (str(self), self.stage, self.node, self.invocation,
                 self.traceback_str))


def _rebuild_data_spill_error(message, uri, partition, op):
    return DataSpillError(message, uri=uri, partition=partition, op=op)


class DataSpillError(RayTpuError):
    """An exchange shard could not be spilled to — or restored from — the
    storage plane (README "Data plane").

    Raised from the exchange's merge/reduce tasks after the bounded
    transient-retry budget is exhausted (e.g. a severed `sim://` spill
    backend): the shuffle fails attributed, never hangs. `uri` names the
    shard that failed, `partition` the reduce partition it belonged to,
    `op` whether the failure was on the `spill` (write) or `restore`
    (read) side.
    """

    def __init__(self, message: str, *, uri: str | None = None,
                 partition: int | None = None, op: str | None = None):
        self.uri = uri
        self.partition = partition
        self.op = op
        super().__init__(message)

    def __reduce__(self):
        return (_rebuild_data_spill_error,
                (str(self), self.uri, self.partition, self.op))


class RuntimeEnvSetupError(RayTpuError):
    """Setting up the runtime environment for a task/actor failed."""


class PendingCallsLimitExceeded(RayTpuError):
    """Actor's pending call queue exceeded max_pending_calls."""


class NodeDiedError(RayTpuError):
    """The node hosting the resource died."""
