"""OpenAI-compatible serving surface for the continuous-batching engine.

Parity target: the reference's OpenAI router + application builder
(python/ray/llm/_internal/serve/deployments/routers/router.py — /v1/models,
/v1/completions, /v1/chat/completions with SSE streaming — and
builders/application_builders.py build_openai_app). The engine behind the
routes is the native TPU ContinuousEngine (llm/engine.py) instead of vLLM;
prompts are strings (byte-level tokenizer) or raw token lists.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Optional

from ray_tpu.llm import LLMConfig
from ray_tpu.llm.engine import ContinuousEngine, GenStream, SamplingParams


class ByteTokenizer:
    """Byte-level tokenizer: token = byte value; BOS=256, EOS=257. Needs
    vocab_size >= 258. Stands in for the reference's HF tokenizer load
    (model_loading_config) — swap in a trained tokenizer the same way."""

    BOS = 256
    EOS = 257
    vocab_size = 258

    def encode(self, text: str, *, bos: bool = True) -> list[int]:
        toks = list(text.encode("utf-8"))
        return ([self.BOS] if bos else []) + toks

    def decode(self, tokens) -> str:
        data = bytes(t for t in tokens if 0 <= t < 256)
        return data.decode("utf-8", "replace")


def _sampling_from_body(body: dict, default_max: int) -> SamplingParams:
    return SamplingParams(
        temperature=float(body.get("temperature", 1.0)),
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 1.0)),
        max_tokens=int(body.get("max_tokens", default_max)),
        stop_token=body.get("stop_token"),
        seed=int(body.get("seed", 0)),
    )


class OpenAIServer:
    """Deployment callable serving /v1/models, /v1/completions and
    /v1/chat/completions (reference LLMRouter + LLMServer collapsed into
    one deployment; the engine IS local, no second hop needed)."""

    def __init__(self, cfg: LLMConfig, model_id: str = "ray-tpu-llm",
                 max_batch: int = 8, decode_chunk: int = 8,
                 default_max_tokens: int = 64,
                 pipeline_stages: Optional[int] = None):
        self.cfg = cfg
        self.model_id = model_id
        self.default_max_tokens = default_max_tokens
        self.tok = ByteTokenizer()
        # pipeline_stages > 1 swaps in the pipeline-parallel engine
        # (README "Pipeline-parallel serving"); None defers to RT_PP_STAGES
        # so a deployment can be re-pointed without a code change. The two
        # engines share the submit()/GenStream surface, so every route —
        # and the serve admission layer above — is engine-agnostic.
        from ray_tpu._private.rtconfig import CONFIG

        stages = (int(CONFIG.pp_stages) if pipeline_stages is None
                  else int(pipeline_stages))
        if stages > 1:
            from ray_tpu.llm.pipeline import PipelinedEngine

            self.engine = PipelinedEngine(
                cfg, n_stages=stages, max_batch=max_batch)
        else:
            self.engine = ContinuousEngine(
                cfg, max_batch=max_batch, decode_chunk=decode_chunk)

    # ------------------------------------------------------------ helpers
    def _encode_prompt(self, body: dict) -> list[int]:
        if "messages" in body:  # chat form
            text = "".join(
                f"<{m.get('role', 'user')}>{m.get('content', '')}"
                for m in body["messages"])
            return self.tok.encode(text)
        prompt = body.get("prompt", "")
        if isinstance(prompt, list):
            return [int(t) for t in prompt]  # raw token ids
        return self.tok.encode(str(prompt))

    def _completion_body(self, req_id: str, text: str, tokens: list[int],
                         finish: Optional[str], chat: bool,
                         stream_delta: bool = False) -> dict:
        if chat:
            key = "delta" if stream_delta else "message"
            choice = {"index": 0, key: {"role": "assistant", "content": text},
                      "finish_reason": finish}
            obj = ("chat.completion.chunk" if stream_delta
                   else "chat.completion")
        else:
            choice = {"index": 0, "text": text, "finish_reason": finish}
            obj = "text_completion"
        return {"id": req_id, "object": obj, "created": int(time.time()),
                "model": self.model_id, "choices": [choice],
                "token_ids": tokens}

    # ------------------------------------------------------------- routes
    def __call__(self, request):
        path = request.path
        if path.endswith("/v1/models") or path.endswith("/models"):
            return {"object": "list",
                    "data": [{"id": self.model_id, "object": "model",
                              "owned_by": "ray_tpu"}]}
        if path.endswith("/v1/stats") or path.endswith("/stats"):
            # Introspection for chaos tests / ops: which process hosts the
            # engine and how many slots are live (a leaked slot shows here).
            out = {"pid": os.getpid(), "active": self.engine.num_active,
                   "running": self.engine._running}
            stages = getattr(self.engine, "n_stages", 0)
            if stages:
                out["pipeline_stages"] = stages
            return out
        body = request.json() or {}
        chat = "chat" in path or "messages" in body
        prompt = self._encode_prompt(body)
        sampling = _sampling_from_body(body, self.default_max_tokens)
        req_id = f"cmpl-{int(time.time() * 1e6):x}"
        stream = self.engine.submit(prompt, sampling)
        if body.get("stream"):
            return self._stream_chunks(req_id, stream, chat)
        toks = stream.tokens()
        return self._completion_body(
            req_id, self.tok.decode(toks), toks, stream.finish_reason, chat)

    def _stream_chunks(self, req_id: str, stream: GenStream, chat: bool):
        """Generator of OpenAI SSE chunk dicts — one per token BATCH
        (GenStream.next_batch drains every token available per wakeup, so
        a chunk of decode output is one dict, one downstream flush — not
        one wakeup and one SSE event per token)."""
        def gen():
            try:
                while True:
                    try:
                        toks = stream.next_batch()
                    except StopIteration:
                        break
                    yield self._completion_body(
                        req_id, self.tok.decode(toks), toks, None, chat,
                        stream_delta=True)
                yield self._completion_body(
                    req_id, "", [], stream.finish_reason or "length", chat,
                    stream_delta=True)
            finally:
                # Consumer gone (client disconnect propagates as
                # GeneratorExit through the serve streaming path): free the
                # engine slot instead of decoding to max_tokens for nobody.
                stream.close()
        return gen()

    def check_health(self):
        if not self.engine._running:
            raise RuntimeError("llm engine stopped")

    def __del__(self):
        try:
            self.engine.shutdown()
        except Exception:
            pass


def build_openai_app(cfg: LLMConfig, *, name: str = "llm",
                     model_id: str = "ray-tpu-llm", num_replicas: int = 1,
                     max_batch: int = 8, decode_chunk: int = 8,
                     default_max_tokens: int = 64,
                     ray_actor_options: Optional[dict] = None,
                     max_ongoing_requests: int = 16,
                     max_queued_requests: int = -1,
                     queue_deadline_s: Optional[float] = None,
                     pipeline_stages: Optional[int] = None):
    """Serve application exposing the OpenAI surface (reference
    build_openai_app, application_builders.py). The admission budgets
    (README "Overload & admission control") pass straight through to the
    deployment: cap ongoing requests near max_batch so excess load sheds
    fast 429s at the proxy instead of stacking onto the engine's queue."""
    from ray_tpu import serve

    dep = serve.deployment(
        OpenAIServer, name=name, num_replicas=num_replicas,
        ray_actor_options=ray_actor_options,
        max_ongoing_requests=max_ongoing_requests,
        max_queued_requests=max_queued_requests,
        queue_deadline_s=queue_deadline_s)
    return dep.bind(cfg, model_id=model_id, max_batch=max_batch,
                    decode_chunk=decode_chunk,
                    default_max_tokens=default_max_tokens,
                    pipeline_stages=pipeline_stages)
