"""Pipeline-parallel LLM decode on the compiled DAG plane.

The ContinuousEngine (engine.py) is one process: the whole model, the
whole KV cache, one device. This module cuts the SAME model at layer
boundaries into N pipeline stages — each a long-lived actor bound into
one compiled DAG (`stage0.step -> stage1.step -> ...`) — and runs decode
iterations as DAG invocations:

- **Stage slicing**: engine.stage_layer_split / stage_param_slice /
  make_stage_net keep per-layer module names GLOBAL (`layer_{i}`), so a
  stage's params are a strict subtree of the full checkpoint and the
  pipelined model is bit-compatible with the single-process one.
- **Microbatched occupancy**: the batch splits into `n_mb` microbatches;
  each decode invocation steps ONE microbatch through all stages, and
  the driver keeps every microbatch's invocation in flight at once, so
  stage k works on microbatch j while stage k+1 works on microbatch j-1
  — classic GPipe-style bubble filling, bounded by RT_DAG_MAX_INFLIGHT.
- **Zero-RPC activation edges**: stage outputs are (tag, mb, activation,
  ...) tuples; the DAG edge publisher pins the activation arrays
  (RT_DAG_EDGE_MIN_BYTES, far below the general device-object threshold)
  and ships ~200B placeholders through the shm channels, eagerly
  exported so a same-host consumer's resolve is a store hit — the steady
  state moves tokens, not activations, and pays no per-token RPC.
- **On-device sampling**: the LAST stage holds the tied head and the
  per-slot sampling mirrors (temperature/top-k/top-p/PRNG keys), so only
  sampled token ids cross back to the driver.
- **Failure contract** (mirrors the DAG plane's): a stage killed
  mid-generation fails every open GenStream with the attributed
  DagStageError (stage name, invocation, node), then the engine tears
  the graph down, rebuilds fresh stages, and resumes from the request
  queue — consumers see a typed error or tokens, never a hang.

Drop-in: PipelinedEngine exposes the ContinuousEngine surface
(`submit() -> GenStream`, `generate`, `shutdown`, `num_active`), so the
serve/OpenAI layer (PR 13 streaming, PR 17 admission control) runs
unchanged on top of it.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import os
import queue
import threading
import time
import uuid
from typing import Any, Optional

import numpy as np

from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.llm.engine import (GenStream, SamplingParams, _count_tokens,
                                _make_sampler, _Slot, make_stage_net,
                                model_config, stage_layer_split,
                                stage_param_slice)

logger = logging.getLogger(__name__)

# ---------------------------------------------------------------- occupancy
#: Cumulative per-stage busy time in THIS process (stage actors record into
#: it from step()). telemetry's WorkerSampler and the metrics drain hook
#: read windowed busy fractions via occupancy_snapshot — the pipeline
#: bubble is (1 - occupancy) of the busiest window.
_occ_lock = threading.Lock()
_occ: dict[str, list] = {}  # stage name -> [busy_seconds, steps]
_occ_marks: dict[str, dict] = {}  # consumer -> stage -> (t, busy_seconds)


def _occ_record(stage: str, busy_s: float) -> None:
    with _occ_lock:
        ent = _occ.setdefault(stage, [0.0, 0])
        ent[0] += busy_s
        ent[1] += 1


def occupancy_snapshot(consumer: str = "telemetry") -> dict:
    """Per-stage busy fraction of wall time since this consumer's previous
    call (first call anchors the window and reports 0.0). Empty dict when
    no pipeline stage lives in this process."""
    now = time.monotonic()
    out: dict[str, float] = {}
    with _occ_lock:
        marks = _occ_marks.setdefault(consumer, {})
        for stage, ent in _occ.items():
            busy = ent[0]
            prev = marks.get(stage)
            marks[stage] = (now, busy)
            if prev is None or now <= prev[0]:
                out[stage] = 0.0
            else:
                out[stage] = min(1.0, max(0.0,
                                          (busy - prev[1]) / (now - prev[0])))
    return out


# ------------------------------------------------------------- stage actor
class PipelineStage:
    """One pipeline stage: a contiguous layer range of the serving model
    plus its OWN per-microbatch KV caches, bound into the compiled DAG via
    `step`. The first stage embeds token ids; the last holds final_norm,
    the tied head, and the sampling state, returning token ids only.

    Messages (the DAG invocation payloads):
      ("d", mb, toks|x, lens, greedy)  one decode step for microbatch `mb`
      ("p", row, toks|x, plen, samp)   prefill one request into batch row
    Mid-pipeline, toks becomes the activation x — a jax.Array the edge
    publisher replaces with a device-object placeholder.
    """

    def __init__(self, cfg, stage_idx: int, n_stages: int, layers: tuple,
                 first: bool, last: bool, shard: dict, mb_size: int,
                 n_mb: int):
        import jax
        import jax.numpy as jnp

        self._jax, self._jnp = jax, jnp
        self.cfg = cfg
        self.name = f"pp{stage_idx}"
        self.first, self.last = bool(first), bool(last)
        self.layers = tuple(layers)
        self.mb_size, self.n_mb = int(mb_size), int(n_mb)
        mcfg = model_config(cfg)
        self.mcfg = mcfg
        self.net = make_stage_net(mcfg, self.layers, self.first, self.last)
        params = jax.tree.map(jnp.asarray, shard)
        if mcfg.dtype == jnp.bfloat16:
            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == jnp.float32 else x, params)
        self.params = params
        self._sampler = _make_sampler(cfg.vocab_size) if self.last else None
        self._build_compiled()
        self._caches = [self._init_cache() for _ in range(self.n_mb)]
        if self.last:
            # Per-microbatch sampling mirrors, set at prefill: decode
            # sampling reads them on device, so the driver never ships
            # sampling state in the steady state.
            self._temps = [jnp.zeros(self.mb_size, jnp.float32)
                           for _ in range(self.n_mb)]
            self._topks = [jnp.zeros(self.mb_size, jnp.int32)
                           for _ in range(self.n_mb)]
            self._topps = [jnp.ones(self.mb_size, jnp.float32)
                           for _ in range(self.n_mb)]
            self._keys = [jax.vmap(jax.random.PRNGKey)(
                jnp.arange(self.mb_size, dtype=jnp.uint32))
                for _ in range(self.n_mb)]

    # ---------------------------------------------------------- compiled
    def _build_compiled(self):
        jax, jnp = self._jax, self._jnp
        net = self.net

        def dstep(params, cache, x, positions):
            y, vars_out = net.apply(
                {"params": params, "cache": cache}, x, positions=positions,
                decode=True, mutable=["cache"])
            return y, vars_out["cache"]

        self._dstep = jax.jit(dstep, donate_argnums=(1,))

        def prefill(params, x):
            positions = jnp.arange(x.shape[1])[None]
            y, vars_out = net.apply(
                {"params": params}, x, positions=positions, decode=True,
                mutable=["cache"])
            return y, vars_out["cache"]

        self._prefill = jax.jit(prefill)

        def place(cache, slice_cache, row):
            return jax.tree.map(
                lambda big, small: jax.lax.dynamic_update_slice(
                    big, small.astype(big.dtype),
                    (row,) + (0,) * (small.ndim - 1)),
                cache, slice_cache)

        self._place = jax.jit(place, donate_argnums=(0,))
        if not self.last:
            return
        sampler = self._sampler

        def psample(y, plen, key, temp, top_k, top_p):
            logits = jax.lax.dynamic_index_in_dim(
                y[0].astype(jnp.float32), plen - 1, 0, keepdims=False)
            return sampler(logits[None], key[None], temp[None],
                           top_k[None], top_p[None])[0]

        self._psample = jax.jit(psample)

        def dsample(y, keys, temp, top_k, top_p):
            split = jax.vmap(jax.random.split)(keys)  # [mb, 2, 2]
            toks = sampler(y[:, -1].astype(jnp.float32), split[:, 1],
                           temp, top_k, top_p)
            return toks, split[:, 0]

        self._dsample = jax.jit(dsample)

        def dgreedy(y):
            return jnp.argmax(y[:, -1], axis=-1).astype(jnp.int32)

        self._dgreedy = jax.jit(dgreedy)

    def _init_cache(self):
        """Zero KV cache for ONE microbatch of this stage's layers (traced
        via eval_shape, exactly like ContinuousEngine._init_cache)."""
        jax, jnp = self._jax, self._jnp
        b = self.mb_size
        if self.first:
            x = jnp.zeros((b, 1), jnp.int32)
        else:
            x = jnp.zeros((b, 1, self.mcfg.d_model), self.mcfg.dtype)
        pos = jnp.zeros((b, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t, pp: self.net.apply(
                {"params": p}, t, positions=pp, decode=True,
                mutable=["cache"])[1]["cache"],
            self.params, x, pos)
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)

    # -------------------------------------------------------------- step
    def step(self, msg):
        t0 = time.monotonic()
        try:
            kind = msg[0]
            if kind == "d":
                return self._step_decode(msg)
            if kind == "p":
                return self._step_prefill(msg)
            raise ValueError(f"unknown pipeline message kind {kind!r}")
        finally:
            _occ_record(self.name, time.monotonic() - t0)

    def _step_decode(self, msg):
        jnp = self._jnp
        _k, mb, x, lens, greedy = msg
        mb = int(mb)
        if self.first:
            x = jnp.asarray(np.asarray(x, np.int32).reshape(self.mb_size, 1))
        positions = jnp.asarray(
            np.asarray(lens, np.int32).reshape(self.mb_size, 1))
        y, self._caches[mb] = self._dstep(
            self.params, self._caches[mb], x, positions)
        if not self.last:
            return ("d", mb, y, lens, greedy)
        if greedy:
            toks = self._dgreedy(y)
        else:
            toks, self._keys[mb] = self._dsample(
                y, self._keys[mb], self._temps[mb], self._topks[mb],
                self._topps[mb])
        # The ONE device->host sync per invocation: token ids, not logits,
        # cross back to the driver.
        return ("d", mb, np.asarray(toks))

    def _step_prefill(self, msg):
        jax, jnp = self._jax, self._jnp
        _k, row, x, plen, samp = msg
        mb, r = divmod(int(row), self.mb_size)
        if self.first:
            x = jnp.asarray(np.asarray(x, np.int32))  # [1, Lb]
        y, cslice = self._prefill(self.params, x)
        self._caches[mb] = self._place(self._caches[mb], cslice,
                                       jnp.int32(r))
        if not self.last:
            return ("p", row, y, plen, samp)
        key = jax.random.fold_in(
            jax.random.PRNGKey(int(samp["seed"])), int(samp["rid"]))
        first = self._psample(
            y, jnp.int32(plen), key, jnp.float32(samp["temperature"]),
            jnp.int32(samp["top_k"]), jnp.float32(samp["top_p"]))
        self._keys[mb] = self._keys[mb].at[r].set(jax.random.fold_in(key, 1))
        self._temps[mb] = self._temps[mb].at[r].set(
            float(samp["temperature"]))
        self._topks[mb] = self._topks[mb].at[r].set(int(samp["top_k"]))
        self._topps[mb] = self._topps[mb].at[r].set(float(samp["top_p"]))
        return ("p", row, int(first))

    # --------------------------------------------------------------- RPC
    def pid(self) -> int:
        return os.getpid()

    def server_addr(self) -> tuple:
        from ray_tpu._private.worker import global_worker

        return tuple(global_worker().server_addr)

    def join_group(self, world_size: int, rank: int, addrs: dict,
                   group_name: str) -> bool:
        """Join the driver-pushed stage group: no KV rendezvous, no
        polling — the address map was negotiated at engine build time,
        exactly like the DAG's channels."""
        from ray_tpu.util import collective

        collective.init_prenegotiated_group(
            world_size, rank,
            {int(k): tuple(v) for k, v in addrs.items()},
            group_name=group_name, connect=True)
        return True

    def edge_stats(self) -> dict:
        """This stage's device-edge resolve counters + busy time (the
        bench's zero-RPC proof reads these)."""
        from ray_tpu._private import device_store

        with _occ_lock:
            ent = _occ.get(self.name, [0.0, 0])
            busy, steps = float(ent[0]), int(ent[1])
        return {"stage": self.name,
                "resolve": device_store.resolve_stats(),
                "busy_s": busy, "steps": steps}

    def reset_stats(self) -> bool:
        from ray_tpu._private import device_store

        device_store.reset_resolve_stats()
        with _occ_lock:
            _occ.pop(self.name, None)
        return True


# ------------------------------------------------------------------ engine
class PipelinedEngine:
    """Pipeline-parallel ContinuousEngine drop-in: same submit()/GenStream
    surface, decode executed as compiled-DAG invocations across N stage
    actors (module docstring has the full design)."""

    def __init__(self, cfg, *, n_stages: int = 2, max_batch: int = 8,
                 microbatch: int = 0, decode_chunk: int = 0, mesh=None,
                 stall_timeout_s: float = 120.0):
        # decode_chunk/mesh are accepted for ContinuousEngine signature
        # compatibility; chunking is replaced by microbatch pipelining and
        # TP meshes live inside stages.
        del decode_chunk, mesh
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import Transformer

        self.cfg = cfg
        self.n_stages = int(n_stages)
        if self.n_stages < 1:
            raise ValueError(f"n_stages ({n_stages}) must be >= 1")
        mb = int(microbatch) or int(CONFIG.pp_microbatch)
        if mb <= 0:
            # Auto: 2 microbatches per stage keeps every stage busy while
            # its neighbours work (the GPipe occupancy rule of thumb).
            mb = max(1, int(max_batch) // (2 * self.n_stages))
        self.mb_size = mb
        self.n_mb = max(2, -(-int(max_batch) // mb))
        self.max_batch = self.mb_size * self.n_mb
        self._stall_s = float(stall_timeout_s)

        mcfg = model_config(cfg)
        model = Transformer(mcfg)
        if cfg.params is not None:
            params = (cfg.params["params"] if "params" in cfg.params
                      else cfg.params)
        else:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = model.init(jax.random.PRNGKey(cfg.seed), dummy)["params"]
        self._splits = stage_layer_split(cfg.n_layers, self.n_stages)
        # Shards ship as numpy (cheap pickles); stage actors re-device-put.
        self._shards = [
            jax.tree.map(np.asarray, stage_param_slice(
                params, layers, s == 0, s == self.n_stages - 1))
            for s, layers in enumerate(self._splits)]
        self._stage_cfg = (dataclasses.replace(cfg, params=None)
                          if cfg.params is not None else cfg)
        del params

        # Host scheduler state (mirrors ContinuousEngine's).
        self._lock = threading.Condition()
        self._pending: "queue.Queue" = queue.Queue()
        self._slots: list[Optional[_Slot]] = [None] * self.max_batch
        self._streams: set = set()
        self._req_counter = itertools.count()
        self._n_active = 0
        self._running = True
        self._rebuilds = 0
        self._mb_toks = np.zeros((self.n_mb, self.mb_size), np.int32)
        self._mb_lens = np.zeros((self.n_mb, self.mb_size), np.int32)
        self._mb_active: list[set] = [set() for _ in range(self.n_mb)]
        self._mb_inflight = [False] * self.n_mb
        self._prefilling: dict[int, tuple] = {}  # slot -> (stream, s, plen)
        self._fifo: collections.deque = collections.deque()
        self._dag = None
        self._actors: list = []
        self._group_name: Optional[str] = None
        self._build_graph()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rt-llm-pp")
        self._thread.start()

    # -------------------------------------------------------------- graph
    def _build_graph(self):
        import ray_tpu
        from ray_tpu import dag as _dag

        stage_cls = ray_tpu.remote(num_cpus=0)(PipelineStage)
        actors = []
        for s, layers in enumerate(self._splits):
            actors.append(stage_cls.remote(
                self._stage_cfg, s, self.n_stages, tuple(layers),
                s == 0, s == self.n_stages - 1, self._shards[s],
                self.mb_size, self.n_mb))
        # Pre-negotiated stage collective group: the driver gathers every
        # stage's listen address and pushes the full rank->addr map at
        # build time (compile-time wiring, like the DAG's channels) —
        # device_store's peer-conn tier then reuses the established conns.
        try:
            addrs = {s: tuple(ray_tpu.get(a.server_addr.remote(),
                                          timeout=60))
                     for s, a in enumerate(actors)}
            gname = f"pp-{uuid.uuid4().hex[:8]}"
            ray_tpu.get([a.join_group.remote(len(actors), s, addrs, gname)
                         for s, a in enumerate(actors)], timeout=60)
            self._group_name = gname
        except Exception:
            logger.exception(
                "pipeline stage-group pre-negotiation failed (stages fall "
                "back to on-demand peer conns)")
        with _dag.InputNode() as inp:
            node = actors[0].step.bind(inp)
            for a in actors[1:]:
                node = a.step.bind(node)
        self._dag = _dag.compile(node)
        self._actors = actors

    def _teardown_graph(self):
        import ray_tpu

        dag, self._dag = self._dag, None
        if dag is not None:
            try:
                dag.teardown()
            except Exception:
                logger.exception("pipeline DAG teardown failed")
        actors, self._actors = self._actors, []
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass

    # -------------------------------------------------------------- public
    def submit(self, prompt_tokens,
               sampling: Optional[SamplingParams] = None) -> GenStream:
        """Queue one request; returns its token stream immediately."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + sampling.max_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens "
                f"({sampling.max_tokens}) exceeds max_seq "
                f"({self.cfg.max_seq})")
        stream = GenStream(next(self._req_counter), len(prompt))
        # Atomic vs shutdown's flag flip (see ContinuousEngine.submit).
        with self._lock:
            if not self._running:
                raise RuntimeError("engine is shut down")
            self._streams.add(stream)
            self._pending.put((prompt, sampling, stream))
            self._lock.notify_all()
        return stream

    def generate(self, prompts,
                 sampling: Optional[SamplingParams] = None
                 ) -> list[list[int]]:
        streams = [self.submit(p, sampling) for p in prompts]
        return [s.tokens() for s in streams]

    def shutdown(self):
        with self._lock:
            self._running = False
            self._lock.notify_all()
        self._pending.put(None)
        self._thread.join(timeout=20)
        self._teardown_graph()
        self._drain_all_streams()

    @property
    def num_active(self) -> int:
        return self._n_active

    def pipeline_stats(self) -> dict:
        """Aggregated per-stage counters: device-edge pins, resolve tiers
        (the zero-RPC proof: resolve_rpcs stays 0 in steady state), and
        per-stage busy time."""
        import ray_tpu

        per = []
        for a in list(self._actors):
            try:
                per.append(ray_tpu.get(a.edge_stats.remote(), timeout=30))
            except Exception:
                pass
        agg = {"edge_pins": 0, "store_hits": 0, "tier0": 0,
               "resolve_rpcs": 0, "stages": per}
        for p in per:
            r = p.get("resolve", {})
            agg["edge_pins"] += int(r.get("edge_pins", 0))
            agg["store_hits"] += int(r.get("store_hit", 0))
            agg["tier0"] += int(r.get("tier0", 0))
            agg["resolve_rpcs"] += (int(r.get("export_rpc", 0))
                                    + int(r.get("fetch", 0)))
        return agg

    def reset_pipeline_stats(self) -> None:
        import ray_tpu

        for a in list(self._actors):
            try:
                ray_tpu.get(a.reset_stats.remote(), timeout=30)
            except Exception:
                pass

    # ----------------------------------------------------------- scheduler
    def _bucket(self, plen: int) -> int:
        b = 8
        while b < plen:
            b *= 2
        return min(b, self.cfg.max_seq)

    def _cap(self) -> int:
        # Outstanding invocations stay under the DAG's own inflight bound,
        # so execute() never blocks the scheduler on the semaphore.
        return max(2, min(int(CONFIG.dag_max_inflight), self.n_mb + 2))

    def _loop(self):
        """Scheduler wrapper: an unexpected scheduler death surfaces an
        attributed error on every open stream — never a hang."""
        error: Optional[Exception] = None
        try:
            self._run_scheduler()
        except Exception as e:  # noqa: BLE001 - terminal: loop is dead
            logger.exception("pipelined llm engine scheduler died")
            error = RuntimeError(
                f"pipelined llm engine scheduler died: {e!r}")
        finally:
            with self._lock:
                self._running = False
            self._drain_all_streams(error)
            self._teardown_graph()

    def _run_scheduler(self):
        while self._running:
            self._admit()
            self._issue_decodes()
            if not self._fifo:
                with self._lock:
                    if self._running and self._pending.empty():
                        self._lock.wait(timeout=0.05)
                continue
            # Fulfill strictly in issue order: the DAG is itself FIFO, so
            # the head ref is always the next to complete.
            kind, ref, meta = self._fifo[0]
            try:
                out = self._get_head(ref)
            except Exception as e:
                self._on_graph_failure(e)
                continue
            if out is None:  # shutdown raced the wait
                continue
            self._fifo.popleft()
            self._rebuilds = 0  # a completed invocation resets the budget
            if kind == "p":
                self._on_prefill_done(out, meta)
            else:
                self._on_decode_done(out, meta)

    def _get_head(self, ref):
        """Head-of-line result wait in shutdown-checked slices; a stall
        past the deadline is a graph failure (never-a-hang)."""
        from ray_tpu.exceptions import GetTimeoutError

        deadline = time.monotonic() + self._stall_s
        while True:
            if not self._running:
                return None
            try:
                return ref.get(timeout=0.25)
            except GetTimeoutError:
                if time.monotonic() > deadline:
                    raise

    def _admit(self):
        cap = self._cap()
        while len(self._fifo) < cap:
            free = next(
                (i for i in range(self.max_batch)
                 if self._slots[i] is None and i not in self._prefilling),
                None)
            if free is None:
                break
            try:
                item = self._pending.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            prompt, sampling, stream = item
            if stream.closed:
                stream.finish_reason = "cancelled"
                self._finish_stream(stream)
                continue
            plen = len(prompt)
            lb = self._bucket(plen)
            toks = np.zeros((1, lb), np.int32)
            toks[0, :plen] = prompt
            samp = {"temperature": float(sampling.temperature),
                    "top_k": int(sampling.top_k),
                    "top_p": float(sampling.top_p),
                    "seed": int(sampling.seed),
                    "rid": int(stream.request_id)}
            try:
                ref = self._dag.execute(("p", free, toks, plen, samp),
                                        timeout=30.0)
            except Exception as e:
                # The graph died before this request started: requeue it
                # (it resumes after the rebuild) and run the failure path.
                self._pending.put((prompt, sampling, stream))
                self._on_graph_failure(e)
                return
            self._prefilling[free] = (stream, sampling, plen)
            self._fifo.append(("p", ref, free))

    def _issue_decodes(self):
        cap = self._cap()
        pre_mbs = {s // self.mb_size for s in self._prefilling}
        for mb in range(self.n_mb):
            if len(self._fifo) >= cap:
                break
            # A microbatch with a prefill in flight must not decode: the
            # decode would land at the stages AFTER the prefill and step
            # the fresh row's cache with a stale position.
            if (self._mb_inflight[mb] or not self._mb_active[mb]
                    or mb in pre_mbs):
                continue
            greedy = all(
                self._slots[mb * self.mb_size + r].sampling.temperature
                <= 0.0 for r in self._mb_active[mb])
            msg = ("d", mb, self._mb_toks[mb].copy(),
                   self._mb_lens[mb].copy(), bool(greedy))
            try:
                ref = self._dag.execute(msg, timeout=30.0)
            except Exception as e:
                self._on_graph_failure(e)
                return
            self._mb_inflight[mb] = True
            self._fifo.append(("d", ref, mb))

    def _on_prefill_done(self, out, slot: int):
        stream, sampling, plen = self._prefilling.pop(slot)
        first = int(out[2])
        if stream.closed:
            stream.finish_reason = "cancelled"
            self._finish_stream(stream)
            return
        st = _Slot(stream, sampling)
        self._slots[slot] = st
        self._n_active += 1
        mb, r = divmod(slot, self.mb_size)
        self._mb_toks[mb][r] = first
        self._mb_lens[mb][r] = plen
        self._deliver(slot, [first])
        if self._slots[slot] is not None:
            self._mb_active[mb].add(r)

    def _on_decode_done(self, out, mb: int):
        self._mb_inflight[mb] = False
        toks = np.asarray(out[2]).reshape(-1)
        for r in sorted(self._mb_active[mb]):
            slot = mb * self.mb_size + r
            tok = int(toks[r])
            self._mb_toks[mb][r] = tok
            self._mb_lens[mb][r] += 1
            self._deliver(slot, [tok])

    def _on_graph_failure(self, e: Exception):
        """The failure contract: fail every open stream with the
        ATTRIBUTED error, tear down, rebuild fresh stages, resume from the
        request queue. Consecutive failures beyond RT_PP_REBUILD_MAX kill
        the engine (the wrapper drains with the terminal error)."""
        logger.warning("pipeline graph failure (%s: %s); rebuilding",
                       type(e).__name__, e)
        self._fifo.clear()
        for slot in list(self._prefilling):
            stream, _s, _p = self._prefilling.pop(slot)
            self._finish_stream(stream, e)
        for i, st in enumerate(self._slots):
            if st is not None:
                self._slots[i] = None
                self._n_active -= 1
                self._finish_stream(st.stream, e)
        for mb in range(self.n_mb):
            self._mb_active[mb].clear()
            self._mb_inflight[mb] = False
        self._mb_toks[:] = 0
        self._mb_lens[:] = 0
        self._teardown_graph()
        self._rebuilds += 1
        limit = max(1, int(CONFIG.pp_rebuild_max))
        if self._rebuilds > limit:
            raise RuntimeError(
                f"pipeline graph failed {self._rebuilds} consecutive times "
                f"(RT_PP_REBUILD_MAX={limit}); last: {e!r}") from e
        self._build_graph()

    # ------------------------------------------------------------ delivery
    def _deliver(self, slot: int, toks: list):
        st = self._slots[slot]
        if st is None:
            return
        if st.stream.closed:
            st.stream.finish_reason = "cancelled"
            self._retire(slot)
            return
        out = toks[:max(0, st.remaining)]
        finish = None
        stop = st.sampling.stop_token
        if stop is not None and stop in out:
            out = out[:out.index(stop) + 1]
            finish = "stop"
        st.emitted += len(out)
        st.remaining -= len(out)
        if finish is None and st.remaining <= 0:
            finish = "length"
        if out:
            st.stream._q.put(out)
            _count_tokens(len(out))
        if finish is not None:
            st.stream.finish_reason = finish
            self._retire(slot)

    def _retire(self, slot: int):
        st = self._slots[slot]
        self._finish_stream(st.stream)
        self._slots[slot] = None
        self._n_active -= 1
        mb, r = divmod(slot, self.mb_size)
        self._mb_active[mb].discard(r)
        # The retired row's cache is garbage until the next prefill places
        # over it; in-flight decodes step it harmlessly (driver discards).

    def _finish_stream(self, stream: GenStream,
                       error: Optional[Exception] = None):
        if error is not None:
            stream._q.put(error)
        stream._q.put(GenStream._DONE)
        with self._lock:
            self._streams.discard(stream)

    def _drain_all_streams(self, error: Optional[Exception] = None):
        while True:
            try:
                item = self._pending.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            _p, _s, stream = item
            self._finish_stream(stream, error)
        with self._lock:
            streams = list(self._streams)
            self._streams.clear()
        for stream in streams:
            if error is not None:
                stream._q.put(error)
            stream._q.put(GenStream._DONE)
