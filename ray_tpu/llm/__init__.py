"""ray_tpu.llm — LLM batch inference + serving on the cluster runtime.

Parity target: reference python/ray/llm (_internal/batch/processor — Data
map_batches pipelines with a stateful model actor; _internal/serve/
deployments/llm/llm_server.py — a Serve deployment wrapping an engine).
The reference delegates the engine to vLLM; here the engine is the native
flagship Transformer with jit'd greedy decoding (a KV cache is the next
optimization seam — decode currently re-forwards the growing context,
which the flash kernel keeps linear in memory).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass
class LLMConfig:
    """reference llm_config.py (model_loading_config + engine args)."""

    vocab_size: int = 512
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 8
    max_seq: int = 256
    max_new_tokens: int = 16
    seed: int = 0
    #: optional pytree of trained params; random init otherwise
    params: Any = None


class LLMEngine:
    """Greedy-decoding engine over the flagship Transformer (the seat the
    reference gives vLLM)."""

    def __init__(self, cfg: LLMConfig):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import Transformer, TransformerConfig

        self.cfg = cfg
        mcfg = TransformerConfig(
            vocab_size=cfg.vocab_size, d_model=cfg.d_model,
            n_layers=cfg.n_layers, n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_heads, d_ff=int(cfg.d_model * 8 / 3) // 8 * 8,
            max_seq=cfg.max_seq, dtype=jnp.float32)
        self.model = Transformer(mcfg)
        if cfg.params is not None:
            self.params = cfg.params
        else:
            dummy = jnp.zeros((1, 8), jnp.int32)
            self.params = self.model.init(
                jax.random.PRNGKey(cfg.seed), dummy)
        self._step = jax.jit(
            lambda p, toks: jnp.argmax(
                self.model.apply(p, toks)[:, -1, :], axis=-1))

    def generate(self, prompts: np.ndarray,
                 max_new_tokens: Optional[int] = None) -> np.ndarray:
        """prompts: [B, S] int32 -> [B, S + new] (greedy)."""
        import jax.numpy as jnp

        toks = jnp.asarray(prompts, jnp.int32)
        n = max_new_tokens or self.cfg.max_new_tokens
        for _ in range(n):
            nxt = self._step(self.params, toks)
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        return np.asarray(toks)


class LLMPredictor:
    """map_batches callable class (reference batch processor's stateful
    UDF): the engine loads once per actor."""

    def __init__(self, cfg: LLMConfig):
        self.engine = LLMEngine(cfg)

    def __call__(self, batch: dict) -> dict:
        out = self.engine.generate(np.asarray(batch["tokens"]))
        return {"tokens": batch["tokens"], "generated": out}


def batch_inference(ds, cfg: LLMConfig, *, concurrency: int = 1):
    """Run generation over a Dataset of {'tokens': [S] int} rows
    (reference llm batch processor: Data pipeline + engine actors)."""
    return ds.map_batches(LLMPredictor, concurrency=concurrency,
                          fn_constructor_args=(cfg,))


def build_llm_deployment(cfg: LLMConfig, *, name: str = "llm",
                         num_replicas: int = 1,
                         ray_actor_options: Optional[dict] = None):
    """A Serve application serving generate() over HTTP/handle (reference
    llm_server.py build_llm_deployment)."""
    from ray_tpu import serve

    @serve.deployment(name=name, num_replicas=num_replicas,
                      ray_actor_options=ray_actor_options)
    class LLMServer:
        def __init__(self, llm_cfg: LLMConfig):
            self.engine = LLMEngine(llm_cfg)

        def __call__(self, request):
            body = request.json()
            prompts = np.asarray(body["tokens"], np.int32)
            if prompts.ndim == 1:
                prompts = prompts[None]
            out = self.engine.generate(
                prompts, body.get("max_new_tokens"))
            return {"generated": out.tolist()}

    return LLMServer.bind(cfg)
