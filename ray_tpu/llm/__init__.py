"""ray_tpu.llm — LLM batch inference + serving on the cluster runtime.

Parity target: reference python/ray/llm (_internal/batch/processor — Data
map_batches pipelines with a stateful model actor; _internal/serve/
deployments/llm/llm_server.py — a Serve deployment wrapping an engine).
The reference delegates the engine to vLLM; here the engine is the native
flagship Transformer with KV-cached greedy decoding: one prefill pass
fills per-layer caches, then every generated token is a fixed-shape
compiled step under lax.scan (see LLMEngine).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

import numpy as np


@dataclass
class LLMConfig:
    """reference llm_config.py (model_loading_config + engine args)."""

    vocab_size: int = 512
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 8
    max_seq: int = 256
    max_new_tokens: int = 16
    seed: int = 0
    #: "bfloat16" halves cache/activation bytes and roughly doubles decode
    #: throughput on TPU; float32 keeps CPU-test numerics exact.
    dtype: str = "float32"
    #: optional pytree of trained params; random init otherwise
    params: Any = None


class LLMEngine:
    """Greedy-decoding engine over the flagship Transformer (the seat the
    reference gives vLLM). KV-cache decode: prefill fills per-layer caches
    in one pass, then every generated token is ONE fixed-shape compiled
    step attending over the cache — O(S) per token instead of the naive
    O(S^2) re-forward of the growing context."""

    def __init__(self, cfg: LLMConfig):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.transformer import Transformer, TransformerConfig

        self.cfg = cfg
        mcfg = TransformerConfig(
            vocab_size=cfg.vocab_size, d_model=cfg.d_model,
            n_layers=cfg.n_layers, n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_heads, d_ff=int(cfg.d_model * 8 / 3) // 8 * 8,
            max_seq=cfg.max_seq, dtype=jnp.dtype(cfg.dtype))
        self.model = Transformer(mcfg)
        if cfg.params is not None:
            self.params = cfg.params
        else:
            dummy = jnp.zeros((1, 8), jnp.int32)
            self.params = self.model.init(
                jax.random.PRNGKey(cfg.seed), dummy)

        def _prefill(params, toks):
            """Full-prompt pass that also fills the KV caches."""
            b, s = toks.shape
            positions = jnp.broadcast_to(jnp.arange(s), (b, s))
            logits, vars_out = self.model.apply(
                params, toks, positions=positions, decode=True,
                mutable=["cache"])
            return jnp.argmax(logits[:, -1, :], axis=-1), vars_out["cache"]

        def _decode(params, cache, first_tok, start_pos, n_steps):
            """n_steps single-token cached steps under ONE lax.scan."""
            def step(carry, _):
                cache, tok, pos = carry
                logits, vars_out = self.model.apply(
                    {**params, "cache": cache}, tok[:, None],
                    positions=pos[:, None], decode=True, mutable=["cache"])
                nxt = jnp.argmax(logits[:, -1, :], axis=-1)
                return (vars_out["cache"], nxt, pos + 1), tok

            # length=n_steps-1: the scan COLLECTS the carried-in token each
            # step, so [first, g2..g_{n-1}] plus the final carry `last`
            # covers all n tokens without a wasted trailing forward pass.
            (cache, last, _), toks = jax.lax.scan(
                step, (cache, first_tok, start_pos), None,
                length=n_steps - 1)
            return jnp.moveaxis(toks, 0, 1), last  # [B, n_steps-1], [B]

        self._prefill = jax.jit(_prefill)
        self._decode = jax.jit(_decode, static_argnums=4)

    def generate(self, prompts: np.ndarray,
                 max_new_tokens: Optional[int] = None) -> np.ndarray:
        """prompts: [B, S] int32 -> [B, S + new] (greedy, KV-cached)."""
        import jax.numpy as jnp

        toks = jnp.asarray(prompts, jnp.int32)
        b, s = toks.shape
        n = max_new_tokens or self.cfg.max_new_tokens
        if s + n > self.cfg.max_seq:
            # The KV cache is a fixed [B, max_seq] buffer; requests past it
            # must fail loudly, not silently return fewer tokens.
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({n}) exceeds the engine's "
                f"max_seq ({self.cfg.max_seq})")
        first, cache = self._prefill({"params": self.params["params"]}, toks)
        start_pos = jnp.full((b,), s, jnp.int32)
        if n == 1:
            return np.asarray(jnp.concatenate([toks, first[:, None]], axis=1))
        gen, last = self._decode({"params": self.params["params"]}, cache,
                                 first, start_pos, n)
        # gen = [first, g2..g_{n-1}] (the scan collects carried-in tokens);
        # `last` completes the n generated tokens.
        out = jnp.concatenate([toks, gen, last[:, None]], axis=1)
        return np.asarray(out)


class LLMPredictor:
    """map_batches callable class (reference batch processor's stateful
    UDF): the engine loads once per actor."""

    def __init__(self, cfg: LLMConfig):
        self.engine = LLMEngine(cfg)

    def __call__(self, batch: dict) -> dict:
        out = self.engine.generate(np.asarray(batch["tokens"]))
        return {"tokens": batch["tokens"], "generated": out}


def batch_inference(ds, cfg: LLMConfig, *, concurrency: int = 1):
    """Run generation over a Dataset of {'tokens': [S] int} rows
    (reference llm batch processor: Data pipeline + engine actors)."""
    return ds.map_batches(LLMPredictor, concurrency=concurrency,
                          fn_constructor_args=(cfg,))


def __getattr__(name):
    # Lazy: the continuous engine / OpenAI surface pull in jax + serve.
    if name in ("ContinuousEngine", "SamplingParams", "GenStream"):
        from ray_tpu.llm import engine as _e

        return getattr(_e, name)
    if name in ("PipelinedEngine", "PipelineStage"):
        from ray_tpu.llm import pipeline as _p

        return getattr(_p, name)
    if name in ("build_openai_app", "OpenAIServer", "ByteTokenizer"):
        from ray_tpu.llm import openai as _o

        return getattr(_o, name)
    raise AttributeError(name)


def build_llm_deployment(cfg: LLMConfig, *, name: str = "llm",
                         num_replicas: int = 1,
                         ray_actor_options: Optional[dict] = None):
    """A Serve application serving generate() over HTTP/handle (reference
    llm_server.py build_llm_deployment)."""
    from ray_tpu import serve

    @serve.deployment(name=name, num_replicas=num_replicas,
                      ray_actor_options=ray_actor_options)
    class LLMServer:
        def __init__(self, llm_cfg: LLMConfig):
            self.engine = LLMEngine(llm_cfg)

        def __call__(self, request):
            body = request.json()
            prompts = np.asarray(body["tokens"], np.int32)
            if prompts.ndim == 1:
                prompts = prompts[None]
            out = self.engine.generate(
                prompts, body.get("max_new_tokens"))
            return {"generated": out.tolist()}

    return LLMServer.bind(cfg)
