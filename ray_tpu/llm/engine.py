"""Continuous-batching LLM engine: the production serving core.

Parity target: the engine seat the reference fills with vLLM
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py —
continuous batching, sampling params, streaming token output, TP-sharded
engine workers via vllm_models.py:123-137). TPU-native design:

- **Slot KV cache**: fixed [max_batch, max_seq] per-layer cache buffers;
  each in-flight request owns one slot. Requests join (bucketed-length
  prefill compiled once per bucket, then a compiled scatter places the
  slot) and leave independently — no lockstep. Fixed shapes mean every
  decode step is the same compiled XLA program; a TPU cannot afford
  vLLM's dynamic block tables, slots are the idiomatic equivalent.
- **Chunked decode**: between admission points the engine runs
  `decode_chunk` single-token steps under ONE lax.scan dispatch,
  amortizing host->device latency while bounding join latency to a few
  tokens. Single-token attention runs the Pallas decode kernel
  (ops/decode_attention.py) against the slot cache.
- **In-graph sampling**: temperature / top-k / top-p / greedy are
  vectorized per-slot inside the compiled step (each slot carries its own
  sampling params and PRNG key), so mixed request settings share a batch.
- **TP over a mesh**: pass `mesh` (axis "tp") and params/caches shard via
  the model's Megatron PartitionSpecs; XLA inserts the ICI collectives.
- **Zero-sync hot loop** (README "Serving hot loop"): decode chunks stay
  pipelined on device with their inputs chained through device-resident
  mirrors; each chunk's token block starts its device→host copy at
  dispatch (`copy_to_host_async`) and is read back one chunk per
  iteration while every younger chunk keeps executing — the XLA stream
  never drains on a readback. Prefill dispatches on its own lane thread
  and splices into the batch at chunk boundaries, so admissions never
  stall steady-state decode. Tokens are DELIVERED in per-chunk batches
  (one consumer wakeup per chunk, not per token).
"""

from __future__ import annotations

import collections
import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ray_tpu._private import tracing as _tracing
from ray_tpu._private.rtconfig import CONFIG

logger = logging.getLogger(__name__)

#: Cumulative tokens delivered to GenStream consumers across every engine
#: in this process — the `llm.tokens_per_s` telemetry series' source
#: (telemetry.WorkerSampler reads the per-tick rate via
#: tokens_per_s_snapshot; sys.modules-gated, so jax-free workers never
#: import this module for it).
_tok_lock = threading.Lock()
_tok_count = 0
_tok_rate_state: list = [None, 0]  # [last snapshot monotonic, last count]


def _count_tokens(n: int) -> None:
    global _tok_count
    with _tok_lock:
        _tok_count += n


def tokens_per_s_snapshot() -> float:
    """Decode-throughput rate since the previous snapshot (telemetry tick
    cadence). First call anchors the window and reports 0."""
    with _tok_lock:
        c = _tok_count
    now = time.monotonic()
    t0, c0 = _tok_rate_state
    _tok_rate_state[0], _tok_rate_state[1] = now, c
    if t0 is None or now <= t0:
        return 0.0
    return (c - c0) / (now - t0)


@dataclass
class SamplingParams:
    """reference vllm SamplingParams subset (the fields the serve layer
    forwards; vllm_engine.py maps OpenAI body fields onto these)."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    max_tokens: int = 16
    stop_token: Optional[int] = None
    seed: int = 0


class GenStream:
    """Host-side token stream of one request: iterate to receive token ids
    as the engine emits them; ends with StopIteration (or raises the
    engine's error).

    Delivery is BATCHED: the engine enqueues one token-id list per decode
    chunk, so a blocked reader wakes once per chunk. `next_batch()`
    exposes the batches directly — it drains every token currently
    available in one call (the serve SSE path coalesces such a batch into
    a single flush); `__next__`/`next()` keep the one-token-at-a-time
    surface on top of the same queue."""

    _DONE = object()

    def __init__(self, request_id: int, prompt_len: int):
        self.request_id = request_id
        self.prompt_len = prompt_len
        self._q: "queue.Queue" = queue.Queue()
        self._buf: collections.deque = collections.deque()
        self._exc: Optional[Exception] = None  # deferred: tokens first
        self.finish_reason: Optional[str] = None
        self.closed = False
        # Trace context captured at submit (README "Tracing & timeline"):
        # the engine scheduler thread parents its per-iteration spans —
        # prefill, chunk dispatch, host-sync readback — to the submitting
        # request's trace, making each per-chunk host round trip visible.
        self.trace: Optional[tuple] = None

    def close(self):
        """Consumer abandoned the request (client disconnect): the engine
        retires the slot at its next emit instead of decoding the full
        max_tokens for nobody (reference: vLLM abort_request)."""
        self.closed = True

    def __iter__(self):
        return self

    def _pop(self, timeout: Optional[float] = None):
        """One token; blocks on the batch queue. Raises StopIteration at
        end of stream, queue.Empty on timeout, or the engine's error."""
        while True:
            if self._buf:
                return self._buf.popleft()
            if self._exc is not None:
                exc, self._exc = self._exc, None
                raise exc
            item = self._q.get(timeout=timeout)
            if item is GenStream._DONE:
                self._q.put(GenStream._DONE)  # idempotent re-next
                raise StopIteration
            if isinstance(item, Exception):
                raise item
            if isinstance(item, list):
                self._buf.extend(item)
            else:
                return item

    def __next__(self):
        return self._pop()

    def next(self, timeout: Optional[float] = None):
        try:
            return self._pop(timeout=timeout)
        except queue.Empty:
            from ray_tpu.exceptions import GetTimeoutError

            # Match ObjectRefGenerator.next: a timeout is a typed runtime
            # error carrying the request identity, not a bare queue.Empty.
            raise GetTimeoutError(
                f"request {self.request_id} yielded no token within "
                f"{timeout}s") from None

    def next_batch(self, timeout: Optional[float] = None) -> list[int]:
        """Every token currently available, blocking only for the first:
        one reader wakeup drains the whole burst (the engine enqueues one
        batch per decode chunk). Raises StopIteration at end of stream and
        GetTimeoutError when nothing arrives in time."""
        out = [self.next(timeout=timeout)]
        while True:
            if self._buf:
                out.append(self._buf.popleft())
                continue
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                return out
            if item is GenStream._DONE:
                self._q.put(GenStream._DONE)  # next call raises Stop
                return out
            if isinstance(item, Exception):
                self._exc = item  # tokens in hand first; raise next call
                return out
            if isinstance(item, list):
                self._buf.extend(item)
            else:
                out.append(item)

    def tokens(self) -> list[int]:
        """Drain the stream to completion."""
        return list(self)


def _make_sampler(vocab: int):
    import jax
    import jax.numpy as jnp

    def sample(logits, keys, temp, top_k, top_p):
        """logits [B, V] f32; keys [B, 2] uint32; temp/top_k/top_p [B].
        temp <= 0 -> greedy. top_k <= 0 -> disabled. top_p >= 1 -> disabled
        (the formula below then keeps every token)."""
        greedy = jnp.argmax(logits, axis=-1)
        lt = logits / jnp.maximum(temp, 1e-6)[:, None]
        sorted_lt = jnp.sort(lt, axis=-1)[:, ::-1]
        k_eff = jnp.clip(jnp.where(top_k > 0, top_k, vocab), 1, vocab)
        kth = jnp.take_along_axis(sorted_lt, (k_eff - 1)[:, None], axis=-1)
        lt = jnp.where(lt < kth, -jnp.inf, lt)
        probs = jax.nn.softmax(lt, axis=-1)
        sp = jnp.sort(probs, axis=-1)[:, ::-1]
        csum = jnp.cumsum(sp, axis=-1)
        # smallest prefix whose mass reaches top_p (always keeps the top
        # token: csum - sp is 0 for it)
        keep = (csum - sp) < top_p[:, None]
        min_keep = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1,
                           keepdims=True)
        lt = jnp.where(probs < min_keep, -jnp.inf, lt)
        sampled = jax.vmap(jax.random.categorical)(keys, lt)
        return jnp.where(temp <= 0.0, greedy, sampled).astype(jnp.int32)

    return sample


class _Slot:
    __slots__ = ("stream", "sampling", "remaining", "emitted")

    def __init__(self, stream: GenStream, sampling: SamplingParams):
        self.stream = stream
        self.sampling = sampling
        self.remaining = sampling.max_tokens
        self.emitted = 0


# ------------------------------------------------------- stage slicing
def model_config(cfg):
    """LLMConfig -> TransformerConfig, the single place the serving model
    shape is derived (ContinuousEngine and the pipeline stages must agree
    bit-for-bit: a pipelined run is the SAME model cut at layer
    boundaries, so matched-parameter A/B comparisons stay honest)."""
    import jax.numpy as jnp

    from ray_tpu.models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=cfg.vocab_size, d_model=cfg.d_model,
        n_layers=cfg.n_layers, n_heads=cfg.n_heads,
        n_kv_heads=cfg.n_heads, d_ff=int(cfg.d_model * 8 / 3) // 8 * 8,
        max_seq=cfg.max_seq, dtype=jnp.dtype(cfg.dtype))


def stage_layer_split(n_layers: int, n_stages: int) -> list[tuple[int, ...]]:
    """Contiguous, balanced layer ranges, one per pipeline stage (the
    remainder layers go to the EARLIEST stages: the last stage already
    carries final_norm + the tied head + the sampler)."""
    if not (1 <= n_stages <= n_layers):
        raise ValueError(
            f"n_stages ({n_stages}) must be in [1, n_layers ({n_layers})]")
    base, rem = divmod(n_layers, n_stages)
    out, start = [], 0
    for s in range(n_stages):
        n = base + (1 if s < rem else 0)
        out.append(tuple(range(start, start + n)))
        start += n
    return out


def stage_param_slice(params: dict, layers: tuple, first: bool,
                      last: bool) -> dict:
    """This stage's shard of a full Transformer param tree. Layer keys keep
    their GLOBAL names (`layer_{i}`) so a shard is a strict subtree of the
    full checkpoint; the embedding rides along on the first stage (embed)
    and the last (tied output head)."""
    out = {}
    if first or last:
        out["tok_emb"] = params["tok_emb"]
    for i in layers:
        out[f"layer_{i}"] = params[f"layer_{i}"]
    if last:
        out["final_norm"] = params["final_norm"]
    return out


def make_stage_net(mcfg, layers: tuple, first: bool, last: bool):
    """Flax module computing one pipeline stage's slice of the Transformer:
    embed (first stage) -> layers[a:b] -> final_norm + tied head (last
    stage). Per-layer module names match the full model's, so
    stage_param_slice output applies directly and a 1-stage net is
    numerically the full Transformer."""
    import flax.linen as nn
    import jax.numpy as jnp

    from ray_tpu.models.transformer import Block, RMSNorm

    class _StageNet(nn.Module):
        @nn.compact
        def __call__(self, x, positions, decode: bool = True):
            emb = None
            if first or last:
                emb = self.param(
                    "tok_emb", nn.initializers.normal(0.02),
                    (mcfg.vocab_size, mcfg.d_model), mcfg.param_dtype)
            if first:
                x = emb[x].astype(mcfg.dtype)
            for i in layers:
                x = Block(mcfg, name=f"layer_{i}")(x, positions,
                                                   decode=decode)
            if last:
                x = RMSNorm(name="final_norm")(x)
                x = jnp.einsum("bsd,vd->bsv", x,
                               emb.astype(mcfg.dtype)).astype(jnp.float32)
            return x

    return _StageNet()


class ContinuousEngine:
    """In-flight-batching engine over the flagship Transformer."""

    def __init__(self, cfg, *, max_batch: int = 8, decode_chunk: int = 8,
                 pipeline_depth: int = 4, mesh=None,
                 prefill_buckets: tuple = ()):
        import jax
        import jax.numpy as jnp

        from ray_tpu.llm import LLMConfig  # noqa: F401 (type)
        from ray_tpu.models.transformer import Transformer

        self.cfg = cfg
        self.max_batch = max_batch
        self.decode_chunk = decode_chunk
        self.pipeline_depth = max(1, pipeline_depth)
        self.mesh = mesh
        mcfg = model_config(cfg)
        self.model = Transformer(mcfg)
        if cfg.params is not None:
            params = cfg.params["params"] if "params" in cfg.params else cfg.params
        else:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(cfg.seed), dummy)["params"]
        if mcfg.dtype == jnp.bfloat16:
            # Inference needs no f32 master weights: pre-cast once so every
            # decode step reads half the bytes (flax would otherwise cast
            # f32->bf16 per call, paying f32 HBM reads each step).
            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == jnp.float32 else x, params)
        if mesh is not None:
            params = self._shard_params(params, mesh)
        self.params = params
        self._sampler = _make_sampler(cfg.vocab_size)
        self._jax = jax
        self._jnp = jnp
        self._build_compiled()

        # Host scheduler state.
        self._lock = threading.Condition()
        self._pending: "queue.Queue" = queue.Queue()
        self._slots: list[Optional[_Slot]] = [None] * max_batch
        self._lengths = np.zeros(max_batch, np.int32)  # next write position
        self._next_tok = np.zeros(max_batch, np.int32)
        # Sampling params live ON DEVICE (updated by .at[].set at admit):
        # steady-state chunk dispatch must transfer nothing host->device.
        self._temps_dev = jnp.zeros(max_batch, jnp.float32)
        self._topks_dev = jnp.zeros(max_batch, jnp.int32)
        self._topps_dev = jnp.ones(max_batch, jnp.float32)
        self._keys = jax.vmap(jax.random.PRNGKey)(
            jnp.arange(max_batch, dtype=jnp.uint32))
        self._cache = None  # created lazily at first admit
        self._req_counter = itertools.count()
        self._n_active = 0
        # Pipelining state: FIFO of dispatched-but-unread chunks, per-slot
        # counts of dispatched-but-unemitted tokens, slots that must not be
        # re-admitted until every in-flight chunk stepping them lands, and
        # device-resident next-token/length mirrors so steady-state chunk
        # dispatch needs NO host->device transfer.
        self._q_chunks: list = []  # [(tokens_device, active, n, tag), ...]
        self._pending_firsts: list = []  # [(slot, first_token_device), ...]
        self._pending_toks = np.zeros(max_batch, np.int64)
        self._cooling: dict[int, Any] = {}
        self._toks_dev = jnp.zeros(max_batch, jnp.int32)
        self._lens_dev = jnp.zeros(max_batch, jnp.int32)
        # Every GenStream not yet _DONE, independent of slot state: the
        # scheduler-death safety net terminates these with an attributed
        # error even when the slot table itself is the casualty.
        self._streams: set = set()
        self._running = True
        # Prefill lane (README "Serving hot loop"): admissions dispatch on
        # their own thread and splice at chunk boundaries via _ready, so a
        # prefill compile/dispatch never blocks the decode loop. Off =
        # inline admission in the scheduler loop (the classic path).
        self._prefill_lane = bool(CONFIG.llm_prefill_lane)
        self._ready: collections.deque = collections.deque()
        self._prefill_inflight = 0
        self._threads = []
        if self._prefill_lane:
            t = threading.Thread(target=self._prefill_loop, daemon=True,
                                 name="rt-llm-prefill")
            t.start()
            self._threads.append(t)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rt-llm-engine")
        self._thread.start()
        self._threads.append(self._thread)

    # ------------------------------------------------------------ sharding
    def _shard_params(self, params, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.models.transformer import param_specs

        specs = param_specs({"params": params})["params"]

        def _filter(spec):
            # Drop mesh axes the caller's mesh doesn't have (e.g. a
            # tp-only serving mesh has no fsdp/ep axis).
            parts = []
            for p in spec:
                if p is None:
                    parts.append(None)
                elif isinstance(p, tuple):
                    kept = tuple(a for a in p if a in mesh.axis_names)
                    parts.append(kept if kept else None)
                else:
                    parts.append(p if p in mesh.axis_names else None)
            return P(*parts)

        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(mesh, _filter(spec))),
            params, specs)

    # ------------------------------------------------------------ compiled
    def _build_compiled(self):
        import functools

        import jax
        import jax.numpy as jnp

        model = self.model
        sampler = self._sampler

        def prefill(params, toks, plen):
            """toks [1, Lb] -> (last-position logits [V], cache slice)."""
            positions = jnp.arange(toks.shape[1])[None]
            logits, vars_out = model.apply(
                {"params": params}, toks, positions=positions, decode=True,
                mutable=["cache"])
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), plen - 1, 0, keepdims=False)
            return last, vars_out["cache"]

        def place(cache, slice_cache, slot):
            """Copy a [1, ...] prefill cache slice into batch row `slot`."""
            return jax.tree.map(
                lambda big, small: jax.lax.dynamic_update_slice(
                    big, small.astype(big.dtype),
                    (slot,) + (0,) * (small.ndim - 1)),
                cache, slice_cache)

        def sample1(logits, key, temp, top_k, top_p):
            return sampler(logits[None], key[None], temp[None], top_k[None],
                           top_p[None])[0]

        def chunk(params, cache, toks, lengths, keys, temp, top_k, top_p,
                  n: int, greedy: bool):
            """n in-flight decode steps under one scan. toks/lengths [B];
            returns (cache, keys, tokens [B, n], lengths [B]). greedy=True
            compiles an argmax-only variant: the sampler's two full-vocab
            sorts per step are pure waste when no active slot samples."""
            def step(carry, _):
                cache, tok, lens, keys = carry
                logits, vars_out = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    positions=lens[:, None], decode=True, mutable=["cache"])
                if greedy:
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
                    keys = split[:, 0]
                    nxt = sampler(logits[:, -1].astype(jnp.float32),
                                  split[:, 1], temp, top_k, top_p)
                return (vars_out["cache"], nxt, lens + 1, keys), nxt

            (cache, _tok, lens, keys), out = jax.lax.scan(
                step, (cache, toks, lengths, keys), None, length=n)
            return cache, keys, jnp.moveaxis(out, 0, 1), lens

        self._prefill = jax.jit(prefill)
        self._place = jax.jit(place, donate_argnums=(0,))
        self._sample1 = jax.jit(sample1)
        self._chunk = jax.jit(chunk, static_argnums=(8, 9),
                              donate_argnums=(1,))

    def _init_cache(self):
        """Zero cache for the full batch, built by tracing one dummy step
        (gives the exact per-layer cache structure at [max_batch, ...])."""
        import jax
        import jax.numpy as jnp

        b = self.max_batch
        toks = jnp.zeros((b, 1), jnp.int32)
        positions = jnp.zeros((b, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t, pos: self.model.apply(
                {"params": p}, t, positions=pos, decode=True,
                mutable=["cache"])[1]["cache"],
            self.params, toks, positions)
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # KV-head axis over tp, matching the attention head sharding.
            def _spec(leaf):
                if leaf.ndim == 4:  # [B, S, KV, D]
                    return NamedSharding(self.mesh, P(None, None, "tp", None))
                return NamedSharding(self.mesh, P())

            cache = jax.tree.map(
                lambda leaf: jax.device_put(leaf, _spec(leaf)), cache)
        return cache

    # -------------------------------------------------------------- public
    def submit(self, prompt_tokens, sampling: Optional[SamplingParams] = None
               ) -> GenStream:
        """Queue one request; returns its token stream immediately."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + sampling.max_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({sampling.max_tokens}) "
                f"exceeds max_seq ({self.cfg.max_seq})")
        stream = GenStream(next(self._req_counter), len(prompt))
        if _tracing.enabled():
            stream.trace = _tracing.current()
        # The _running check and the enqueue must be ONE atomic step
        # against shutdown()'s flag flip: a submit that slips between the
        # check and the put could otherwise queue a stream after the
        # scheduler's final drain — stranding it without _DONE forever.
        with self._lock:
            if not self._running:
                raise RuntimeError("engine is shut down")
            self._streams.add(stream)
            self._pending.put((prompt, sampling, stream))
            self._lock.notify_all()
        return stream

    def generate(self, prompts, sampling: Optional[SamplingParams] = None
                 ) -> list[list[int]]:
        """Batch convenience: submit all, drain all."""
        streams = [self.submit(p, sampling) for p in prompts]
        return [s.tokens() for s in streams]

    def shutdown(self):
        with self._lock:
            self._running = False
            self._lock.notify_all()
        self._pending.put(None)  # wake the prefill lane past its get()
        for t in self._threads:
            t.join(timeout=10)
        # Belt and braces after the join: the scheduler thread drains
        # _pending on exit, but if the join timed out (thread wedged in a
        # device call) any queued streams would hang their consumers —
        # terminate them here. Safe against the loop's own drain (done
        # markers are idempotent) because no new submit can enqueue after
        # the flag flipped under the lock.
        self._drain_all_streams()

    def _drain_all_streams(self, error: Optional[Exception] = None):
        """Terminate every stream that has not seen _DONE: queued, ready,
        slotted, or otherwise tracked. Idempotent (done markers re-queue
        harmlessly); the error, when given, lands before the marker."""
        while True:
            try:
                item = self._pending.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            _p, _s, stream = item
            self._finish_stream(stream, error)
        with self._lock:
            streams = list(self._streams)
            self._streams.clear()
        for stream in streams:
            if error is not None:
                stream._q.put(error)
            stream._q.put(GenStream._DONE)

    def _finish_stream(self, stream: GenStream,
                       error: Optional[Exception] = None):
        if error is not None:
            stream._q.put(error)
        stream._q.put(GenStream._DONE)
        with self._lock:
            self._streams.discard(stream)

    @property
    def num_active(self) -> int:
        return self._n_active

    # ----------------------------------------------------------- scheduler
    def _bucket(self, plen: int) -> int:
        b = 8
        while b < plen:
            b *= 2
        return min(b, self.cfg.max_seq)

    def _prefill_dispatch(self, prompt, sampling, stream):
        """Dispatch bucketed prefill + first-token sample WITHOUT reading
        anything back: returns (first_token_dev, cache_slice, next_key) —
        pure device handles, safe to produce off the scheduler thread (no
        shared scheduler state is touched)."""
        import jax.numpy as jnp

        plen = len(prompt)
        lb = self._bucket(plen)
        toks = np.zeros((1, lb), np.int32)
        toks[0, :plen] = prompt
        t_adm = time.time()
        last_logits, cache_slice = self._prefill(
            self.params, jnp.asarray(toks), plen)
        key = self._jax.random.fold_in(
            self._jax.random.PRNGKey(sampling.seed), stream.request_id)
        first = self._sample1(
            last_logits, key,
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k), jnp.float32(sampling.top_p))
        _tracing.record_span_in(
            stream.trace, "engine.prefill", "engine", t_adm, time.time(),
            {"prompt_len": plen})
        return first, cache_slice, self._jax.random.fold_in(key, 1)

    def _prefill_loop(self):
        """The prefill lane: drains submits, dispatches their prefills,
        and parks the device-resident results in _ready for the scheduler
        to splice at the next chunk boundary. Prefill COMPILES (new
        buckets) and dispatches happen here — the decode loop never
        stalls for an admission."""
        while True:
            try:
                item = self._pending.get(timeout=0.25)
            except queue.Empty:
                if not self._running:
                    return
                continue
            if item is None:  # shutdown wakeup
                if not self._running:
                    return
                continue
            prompt, sampling, stream = item
            if not self._running:
                # Shutdown raced the pop: terminate the stream instead of
                # compiling/dispatching a prefill nobody will consume (a
                # cold bucket compile here would stall shutdown's join).
                self._finish_stream(stream)
                continue
            if stream.closed:
                stream.finish_reason = "cancelled"
                self._finish_stream(stream)
                continue
            # inflight guards the scheduler's idle-wait: a popped submit
            # whose prefill is still dispatching must keep the loop from
            # concluding "nothing pending" (it would only cost the 0.1s
            # wait timeout, but the first token is latency-critical).
            with self._lock:
                self._prefill_inflight += 1
            try:
                entry = (len(prompt), sampling, stream,
                         *self._prefill_dispatch(prompt, sampling, stream))
            except Exception as e:  # bad request or device failure
                with self._lock:
                    self._prefill_inflight -= 1
                self._finish_stream(stream, e)
                continue
            with self._lock:
                self._ready.append(entry)
                self._prefill_inflight -= 1
                self._lock.notify_all()

    def _splice(self, slot: int, plen: int, sampling, stream, first,
                cache_slice, key):
        """Install one prefilled request into batch row `slot` (scheduler
        thread only — this is the chunk-boundary splice point): scatter
        the cache slice, set the device mirrors, book the slot."""
        if self._cache is None:
            self._cache = self._init_cache()
        self._cache = self._place(self._cache, cache_slice,
                                  self._jnp.int32(slot))
        st = _Slot(stream, sampling)
        self._slots[slot] = st
        self._n_active += 1
        self._lengths[slot] = plen
        self._pending_toks[slot] = 0
        self._temps_dev = self._temps_dev.at[slot].set(sampling.temperature)
        self._topks_dev = self._topks_dev.at[slot].set(sampling.top_k)
        self._topps_dev = self._topps_dev.at[slot].set(sampling.top_p)
        self._keys = self._keys.at[slot].set(key)
        self._pending_firsts.append((slot, first))
        # Merge into the device mirrors without a sync.
        self._toks_dev = self._toks_dev.at[slot].set(first)
        self._lens_dev = self._lens_dev.at[slot].set(int(plen))

    def _admit_async(self, slot: int, prompt, sampling, stream):
        """Inline admission (prefill lane off): dispatch prefill + first-
        token sample + cache place for one slot WITHOUT reading the result
        back (first tokens join the next drain's readback — each read is a
        full round trip on tunneled/remote TPUs)."""
        first, cache_slice, key = self._prefill_dispatch(
            prompt, sampling, stream)
        self._splice(slot, len(prompt), sampling, stream, first,
                     cache_slice, key)

    def _free_slot(self, taken=()) -> Optional[int]:
        return next((i for i, s in enumerate(self._slots)
                     if s is None and i not in self._cooling
                     and i not in taken), None)

    def _deliver(self, slot: int, toks: list):
        """Hand one chunk's tokens for `slot` to its stream as ONE queue
        put (a blocked reader wakes once per chunk, not once per token),
        applying stop-token / length truncation host-side."""
        st = self._slots[slot]
        if st is None:
            return
        if st.stream.closed:
            st.stream.finish_reason = "cancelled"
            self._retire(slot)
            return
        out = toks[:max(0, st.remaining)]
        finish = None
        stop = st.sampling.stop_token
        if stop is not None and stop in out:
            out = out[:out.index(stop) + 1]
            finish = "stop"
        st.emitted += len(out)
        st.remaining -= len(out)
        if finish is None and st.remaining <= 0:
            finish = "length"
        if out:
            st.stream._q.put(out)
            _count_tokens(len(out))
        if finish is not None:
            st.stream.finish_reason = finish
            self._retire(slot)

    def _retire(self, slot: int):
        st = self._slots[slot]
        self._finish_stream(st.stream)
        self._slots[slot] = None
        self._n_active -= 1
        self._lengths[slot] = 0
        self._next_tok[slot] = 0
        # (device-side sampling mirrors keep stale values for retired
        # slots; the slot decodes garbage that deliver discards)
        if self._q_chunks and slot in self._q_chunks[-1][1]:
            # Already-dispatched chunks still step this slot; it must not
            # be re-admitted until the NEWEST of them is emitted (device
            # program order makes the cache safe — this guards only the
            # host-side slot bookkeeping).
            self._cooling[slot] = self._q_chunks[-1][3]

    def _loop(self):
        """Scheduler wrapper: an unexpected scheduler death must surface
        an attributed error on EVERY open stream (queued, ready, or
        decoding) — a consumer blocked in next() can never hang on a dead
        engine. Normal exit drains the same way without the error."""
        error: Optional[Exception] = None
        try:
            self._run_scheduler()
        except Exception as e:  # noqa: BLE001 - terminal: loop is dead
            logger.exception("llm engine scheduler loop died")
            error = RuntimeError(f"llm engine scheduler died: {e!r}")
        finally:
            with self._lock:
                self._running = False
            self._drain_all_streams(error)

    def _run_scheduler(self):
        """Scheduler with depth-D software pipelining. Host syncs are the
        scarce resource (a tunneled/remote TPU pays ~100ms per blocking
        read): up to `pipeline_depth` decode chunks stay in flight with
        their inputs chained ENTIRELY on device (next-token/length mirrors
        ride chunk outputs, so steady-state dispatch transfers nothing).
        Each chunk's token block starts its device→host copy AT DISPATCH
        (copy_to_host_async) and is read back one chunk per iteration —
        double-buffered extraction: reading chunk N overlaps the execution
        of chunks N+1..N+D-1, so the XLA stream never drains. Correctness
        leans on device program order (place/chunk chain through the cache
        handle); the host only avoids re-admitting a slot an in-flight
        chunk still steps (the _cooling set)."""
        import jax.numpy as jnp

        while self._running:
            # ---- 1. admissions: splice prefilled requests at the chunk
            # boundary (prefill lane), or run the classic inline admission
            # (lane off). Either way nothing here reads from device.
            if self._prefill_lane:
                while self._n_active < self.max_batch:
                    free = self._free_slot()
                    if free is None:
                        break
                    with self._lock:
                        if not self._ready:
                            break
                        entry = self._ready.popleft()
                    plen, sampling, stream, first, cache_slice, key = entry
                    if stream.closed:
                        stream.finish_reason = "cancelled"
                        self._finish_stream(stream)
                        continue
                    try:
                        self._splice(free, plen, sampling, stream, first,
                                     cache_slice, key)
                    except Exception as e:
                        self._finish_stream(stream, e)
            else:
                while self._n_active < self.max_batch:
                    free = self._free_slot()
                    if free is None:
                        break
                    try:
                        item = self._pending.get_nowait()
                    except queue.Empty:
                        break
                    if item is None:
                        continue
                    prompt, sampling, stream = item
                    try:
                        self._admit_async(free, prompt, sampling, stream)
                    except Exception as e:  # bad request or engine failure
                        self._finish_stream(stream, e)
            # First tokens are NOT read at admission: they join the next
            # drain's readback (an admission-wave readback would cost its
            # own ~100ms round trip on tunneled TPUs).
            if (self._n_active == 0 and not self._q_chunks
                    and not self._pending_firsts):
                with self._lock:
                    if (self._running and self._pending.empty()
                            and not self._ready
                            and self._prefill_inflight == 0):
                        self._lock.wait(timeout=0.1)
                continue
            # ---- 2. fill the pipeline: dispatch up to pipeline_depth
            # chunks back to back (dispatches are asynchronous and nearly
            # free; only the readback costs a round trip)
            while len(self._q_chunks) < self.pipeline_depth:
                if (self._prefill_lane and self._ready
                        and self._n_active < self.max_batch
                        and self._free_slot() is not None):
                    # A prefilled request is waiting and a slot is open:
                    # stop filling the pipeline with the OLD batch and
                    # splice at this chunk boundary (next iteration's
                    # admission step) — join latency stays a few tokens.
                    break
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    break
                budget = int(min(
                    min(self._slots[i].remaining - self._pending_toks[i]
                        for i in active),
                    min(self.cfg.max_seq - int(self._lengths[i])
                        for i in active)))
                if budget < 1:
                    break  # every active slot's fate is already in flight
                # Power-of-2 chunk sizes only: each distinct scan length
                # is its own compiled program, and an arbitrary shrinking
                # budget would recompile on nearly every call.
                n = max(1, min(self.decode_chunk,
                               1 << (budget.bit_length() - 1)))
                greedy = all(
                    self._slots[i].sampling.temperature <= 0.0
                    for i in active)
                # Per-iteration tracing (README "Tracing & timeline"): bind
                # the decode loop's spans to the oldest active TRACED
                # request — in the one-request case (the BENCH_r05 gap's
                # shape) every dispatch and host sync lands in its timeline.
                tctx = next((self._slots[i].stream.trace for i in active
                             if self._slots[i].stream.trace is not None),
                            None)
                try:
                    t_disp = time.time()
                    self._cache, self._keys, toks_out, lens_out = \
                        self._chunk(
                            self.params, self._cache,
                            self._toks_dev, self._lens_dev,
                            self._keys, self._temps_dev,
                            self._topks_dev, self._topps_dev, n, greedy)
                    # Start the device→host copy of this chunk's tokens
                    # NOW: by the time the drain reads it (D iterations
                    # later), the transfer has overlapped the younger
                    # chunks' execution instead of serializing after it.
                    try:
                        toks_out.copy_to_host_async()
                    except Exception:
                        pass  # backend without async copy: read pays it
                    _tracing.record_span_in(
                        tctx, "engine.dispatch_chunk", "engine", t_disp,
                        time.time(), {"tokens": n, "active": len(active)})
                    # Chain on device; mirror lengths on host (every slot
                    # steps n times — deterministic, no read needed).
                    self._toks_dev = toks_out[:, n - 1]
                    self._lens_dev = lens_out
                    self._lengths = self._lengths + n
                    for i in active:
                        self._pending_toks[i] += n
                    self._q_chunks.append((toks_out, active, n, object()))
                except Exception as e:
                    logger.exception("llm engine decode chunk failed")
                    for i in active:
                        self._slots[i].stream._q.put(e)
                        self._retire(i)
                    break
            # ---- 3. drain: read the OLDEST in-flight chunk (plus any
            # admission wave's first tokens) in one device sync, leaving
            # the younger chunks executing — the double buffer. One
            # host_sync per chunk: a request's span count is bounded by
            # its CHUNK count, never its token count.
            if self._q_chunks or self._pending_firsts:
                q = self._q_chunks[:1]
                del self._q_chunks[:1]
                firsts, self._pending_firsts = self._pending_firsts, []
                parts = []
                if firsts:
                    col = jnp.zeros((self.max_batch, 1), jnp.int32)
                    for slot, fdev in firsts:
                        col = col.at[slot, 0].set(fdev)
                    parts.append(col)
                parts.extend(c[0] for c in q)
                # The host-sync readback: THE per-iteration host-link round
                # trip the decode loop pays (the 22x end-to-end gap in
                # BENCH_r05 was made of these, one per TOKEN; now one per
                # chunk, overlapped). Span it against the oldest traced
                # in-flight request + the decode-step histogram.
                sync_ctx = None
                if _tracing.enabled():
                    sync_ctx = next(
                        (self._slots[i].stream.trace
                         for _t, p_active, _n, _tag in q for i in p_active
                         if self._slots[i] is not None
                         and self._slots[i].stream.trace is not None),
                        None)
                    if sync_ctx is None:
                        sync_ctx = next(
                            (self._slots[s].stream.trace
                             for s, _f in firsts
                             if self._slots[s] is not None
                             and self._slots[s].stream.trace is not None),
                            None)
                t_sync = time.time()
                try:
                    all_np = np.asarray(
                        parts[0] if len(parts) == 1
                        else jnp.concatenate(parts, axis=1))
                except Exception as e:
                    for slot, _f in firsts:
                        if self._slots[slot] is not None:
                            self._slots[slot].stream._q.put(e)
                            self._retire(slot)
                    for _t, p_active, _n, _tag in q:
                        for i in p_active:
                            if self._slots[i] is not None:
                                self._slots[i].stream._q.put(e)
                                self._retire(i)
                    all_np = None
                if sync_ctx is not None and all_np is not None:
                    t_end = time.time()
                    _tracing.record_span_in(
                        sync_ctx, "engine.host_sync", "engine", t_sync,
                        t_end, {"chunks": len(q),
                                "cols": int(all_np.shape[1])})
                    try:
                        from ray_tpu.util import metrics as _metrics

                        _metrics.DECODE_STEP_SECONDS.observe(t_end - t_sync)
                    except Exception:
                        pass
                off = 0
                if firsts and all_np is not None:
                    for slot, _f in firsts:
                        if self._slots[slot] is None:
                            continue  # retired by a failed-dispatch path
                        self._next_tok[slot] = int(all_np[slot, 0])
                        self._deliver(slot, [int(all_np[slot, 0])])
                if firsts:
                    off = 1
                for _toks_dev, p_active, pn, tag in q:
                    if all_np is not None:
                        for i in p_active:
                            self._pending_toks[i] = max(
                                0, self._pending_toks[i] - pn)
                            if self._slots[i] is None:
                                continue  # retired; tail is garbage
                            toks = [int(all_np[i, j])
                                    for j in range(off, off + pn)]
                            self._deliver(i, toks)
                            if self._slots[i] is not None:
                                self._next_tok[i] = int(
                                    all_np[i, off + pn - 1])
                    off += pn
                    self._cooling = {s: t for s, t in self._cooling.items()
                                     if t is not tag}
