"""Continuous-batching LLM engine: the production serving core.

Parity target: the engine seat the reference fills with vLLM
(python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py —
continuous batching, sampling params, streaming token output, TP-sharded
engine workers via vllm_models.py:123-137). TPU-native design:

- **Slot KV cache**: fixed [max_batch, max_seq] per-layer cache buffers;
  each in-flight request owns one slot. Requests join (bucketed-length
  prefill compiled once per bucket, then a compiled scatter places the
  slot) and leave independently — no lockstep. Fixed shapes mean every
  decode step is the same compiled XLA program; a TPU cannot afford
  vLLM's dynamic block tables, slots are the idiomatic equivalent.
- **Chunked decode**: between admission points the engine runs
  `decode_chunk` single-token steps under ONE lax.scan dispatch,
  amortizing host->device latency while bounding join latency to a few
  tokens. Single-token attention runs the Pallas decode kernel
  (ops/decode_attention.py) against the slot cache.
- **In-graph sampling**: temperature / top-k / top-p / greedy are
  vectorized per-slot inside the compiled step (each slot carries its own
  sampling params and PRNG key), so mixed request settings share a batch.
- **TP over a mesh**: pass `mesh` (axis "tp") and params/caches shard via
  the model's Megatron PartitionSpecs; XLA inserts the ICI collectives.
"""

from __future__ import annotations

import itertools
import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

import numpy as np

from ray_tpu._private import tracing as _tracing

logger = logging.getLogger(__name__)


@dataclass
class SamplingParams:
    """reference vllm SamplingParams subset (the fields the serve layer
    forwards; vllm_engine.py maps OpenAI body fields onto these)."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 1.0
    max_tokens: int = 16
    stop_token: Optional[int] = None
    seed: int = 0


class GenStream:
    """Host-side token stream of one request: iterate to receive token ids
    as the engine emits them; ends with StopIteration (or raises the
    engine's error)."""

    _DONE = object()

    def __init__(self, request_id: int, prompt_len: int):
        self.request_id = request_id
        self.prompt_len = prompt_len
        self._q: "queue.Queue" = queue.Queue()
        self.finish_reason: Optional[str] = None
        self.closed = False
        # Trace context captured at submit (README "Tracing & timeline"):
        # the engine scheduler thread parents its per-iteration spans —
        # prefill, chunk dispatch, host-sync readback — to the submitting
        # request's trace, making each per-token host round trip visible.
        self.trace: Optional[tuple] = None

    def close(self):
        """Consumer abandoned the request (client disconnect): the engine
        retires the slot at its next emit instead of decoding the full
        max_tokens for nobody (reference: vLLM abort_request)."""
        self.closed = True

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is GenStream._DONE:
            self._q.put(GenStream._DONE)  # idempotent re-next
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def next(self, timeout: Optional[float] = None):
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            from ray_tpu.exceptions import GetTimeoutError

            # Match ObjectRefGenerator.next: a timeout is a typed runtime
            # error carrying the request identity, not a bare queue.Empty.
            raise GetTimeoutError(
                f"request {self.request_id} yielded no token within "
                f"{timeout}s") from None
        if item is GenStream._DONE:
            self._q.put(GenStream._DONE)
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        return item

    def tokens(self) -> list[int]:
        """Drain the stream to completion."""
        return list(self)


def _make_sampler(vocab: int):
    import jax
    import jax.numpy as jnp

    def sample(logits, keys, temp, top_k, top_p):
        """logits [B, V] f32; keys [B, 2] uint32; temp/top_k/top_p [B].
        temp <= 0 -> greedy. top_k <= 0 -> disabled. top_p >= 1 -> disabled
        (the formula below then keeps every token)."""
        greedy = jnp.argmax(logits, axis=-1)
        lt = logits / jnp.maximum(temp, 1e-6)[:, None]
        sorted_lt = jnp.sort(lt, axis=-1)[:, ::-1]
        k_eff = jnp.clip(jnp.where(top_k > 0, top_k, vocab), 1, vocab)
        kth = jnp.take_along_axis(sorted_lt, (k_eff - 1)[:, None], axis=-1)
        lt = jnp.where(lt < kth, -jnp.inf, lt)
        probs = jax.nn.softmax(lt, axis=-1)
        sp = jnp.sort(probs, axis=-1)[:, ::-1]
        csum = jnp.cumsum(sp, axis=-1)
        # smallest prefix whose mass reaches top_p (always keeps the top
        # token: csum - sp is 0 for it)
        keep = (csum - sp) < top_p[:, None]
        min_keep = jnp.min(jnp.where(keep, sp, jnp.inf), axis=-1,
                           keepdims=True)
        lt = jnp.where(probs < min_keep, -jnp.inf, lt)
        sampled = jax.vmap(jax.random.categorical)(keys, lt)
        return jnp.where(temp <= 0.0, greedy, sampled).astype(jnp.int32)

    return sample


class _Slot:
    __slots__ = ("stream", "sampling", "remaining", "emitted")

    def __init__(self, stream: GenStream, sampling: SamplingParams):
        self.stream = stream
        self.sampling = sampling
        self.remaining = sampling.max_tokens
        self.emitted = 0


class ContinuousEngine:
    """In-flight-batching engine over the flagship Transformer."""

    def __init__(self, cfg, *, max_batch: int = 8, decode_chunk: int = 8,
                 pipeline_depth: int = 4, mesh=None,
                 prefill_buckets: tuple = ()):
        import jax
        import jax.numpy as jnp

        from ray_tpu.llm import LLMConfig  # noqa: F401 (type)
        from ray_tpu.models.transformer import Transformer, TransformerConfig

        self.cfg = cfg
        self.max_batch = max_batch
        self.decode_chunk = decode_chunk
        self.pipeline_depth = max(1, pipeline_depth)
        self.mesh = mesh
        mcfg = TransformerConfig(
            vocab_size=cfg.vocab_size, d_model=cfg.d_model,
            n_layers=cfg.n_layers, n_heads=cfg.n_heads,
            n_kv_heads=cfg.n_heads, d_ff=int(cfg.d_model * 8 / 3) // 8 * 8,
            max_seq=cfg.max_seq, dtype=jnp.dtype(cfg.dtype))
        self.model = Transformer(mcfg)
        if cfg.params is not None:
            params = cfg.params["params"] if "params" in cfg.params else cfg.params
        else:
            dummy = jnp.zeros((1, 8), jnp.int32)
            params = self.model.init(jax.random.PRNGKey(cfg.seed), dummy)["params"]
        if mcfg.dtype == jnp.bfloat16:
            # Inference needs no f32 master weights: pre-cast once so every
            # decode step reads half the bytes (flax would otherwise cast
            # f32->bf16 per call, paying f32 HBM reads each step).
            params = jax.tree.map(
                lambda x: x.astype(jnp.bfloat16)
                if x.dtype == jnp.float32 else x, params)
        if mesh is not None:
            params = self._shard_params(params, mesh)
        self.params = params
        self._sampler = _make_sampler(cfg.vocab_size)
        self._jax = jax
        self._jnp = jnp
        self._build_compiled()

        # Host scheduler state.
        self._lock = threading.Condition()
        self._pending: "queue.Queue" = queue.Queue()
        self._slots: list[Optional[_Slot]] = [None] * max_batch
        self._lengths = np.zeros(max_batch, np.int32)  # next write position
        self._next_tok = np.zeros(max_batch, np.int32)
        # Sampling params live ON DEVICE (updated by .at[].set at admit):
        # steady-state chunk dispatch must transfer nothing host->device.
        self._temps_dev = jnp.zeros(max_batch, jnp.float32)
        self._topks_dev = jnp.zeros(max_batch, jnp.int32)
        self._topps_dev = jnp.ones(max_batch, jnp.float32)
        self._keys = jax.vmap(jax.random.PRNGKey)(
            jnp.arange(max_batch, dtype=jnp.uint32))
        self._cache = None  # created lazily at first admit
        self._req_counter = itertools.count()
        self._n_active = 0
        # Pipelining state: FIFO of dispatched-but-unread chunks, per-slot
        # counts of dispatched-but-unemitted tokens, slots that must not be
        # re-admitted until every in-flight chunk stepping them lands, and
        # device-resident next-token/length mirrors so steady-state chunk
        # dispatch needs NO host->device transfer.
        self._q_chunks: list = []  # [(tokens_device, active, n, tag), ...]
        self._pending_firsts: list = []  # [(slot, first_token_device), ...]
        self._pending_toks = np.zeros(max_batch, np.int64)
        self._cooling: dict[int, Any] = {}
        self._toks_dev = jnp.zeros(max_batch, jnp.int32)
        self._lens_dev = jnp.zeros(max_batch, jnp.int32)
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rt-llm-engine")
        self._thread.start()

    # ------------------------------------------------------------ sharding
    def _shard_params(self, params, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ray_tpu.models.transformer import param_specs

        specs = param_specs({"params": params})["params"]

        def _filter(spec):
            # Drop mesh axes the caller's mesh doesn't have (e.g. a
            # tp-only serving mesh has no fsdp/ep axis).
            parts = []
            for p in spec:
                if p is None:
                    parts.append(None)
                elif isinstance(p, tuple):
                    kept = tuple(a for a in p if a in mesh.axis_names)
                    parts.append(kept if kept else None)
                else:
                    parts.append(p if p in mesh.axis_names else None)
            return P(*parts)

        return jax.tree.map(
            lambda leaf, spec: jax.device_put(
                leaf, NamedSharding(mesh, _filter(spec))),
            params, specs)

    # ------------------------------------------------------------ compiled
    def _build_compiled(self):
        import functools

        import jax
        import jax.numpy as jnp

        model = self.model
        sampler = self._sampler

        def prefill(params, toks, plen):
            """toks [1, Lb] -> (last-position logits [V], cache slice)."""
            positions = jnp.arange(toks.shape[1])[None]
            logits, vars_out = model.apply(
                {"params": params}, toks, positions=positions, decode=True,
                mutable=["cache"])
            last = jax.lax.dynamic_index_in_dim(
                logits[0].astype(jnp.float32), plen - 1, 0, keepdims=False)
            return last, vars_out["cache"]

        def place(cache, slice_cache, slot):
            """Copy a [1, ...] prefill cache slice into batch row `slot`."""
            return jax.tree.map(
                lambda big, small: jax.lax.dynamic_update_slice(
                    big, small.astype(big.dtype),
                    (slot,) + (0,) * (small.ndim - 1)),
                cache, slice_cache)

        def sample1(logits, key, temp, top_k, top_p):
            return sampler(logits[None], key[None], temp[None], top_k[None],
                           top_p[None])[0]

        def chunk(params, cache, toks, lengths, keys, temp, top_k, top_p,
                  n: int, greedy: bool):
            """n in-flight decode steps under one scan. toks/lengths [B];
            returns (cache, keys, tokens [B, n], lengths [B]). greedy=True
            compiles an argmax-only variant: the sampler's two full-vocab
            sorts per step are pure waste when no active slot samples."""
            def step(carry, _):
                cache, tok, lens, keys = carry
                logits, vars_out = model.apply(
                    {"params": params, "cache": cache}, tok[:, None],
                    positions=lens[:, None], decode=True, mutable=["cache"])
                if greedy:
                    nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                else:
                    split = jax.vmap(jax.random.split)(keys)  # [B, 2, 2]
                    keys = split[:, 0]
                    nxt = sampler(logits[:, -1].astype(jnp.float32),
                                  split[:, 1], temp, top_k, top_p)
                return (vars_out["cache"], nxt, lens + 1, keys), nxt

            (cache, _tok, lens, keys), out = jax.lax.scan(
                step, (cache, toks, lengths, keys), None, length=n)
            return cache, keys, jnp.moveaxis(out, 0, 1), lens

        self._prefill = jax.jit(prefill)
        self._place = jax.jit(place, donate_argnums=(0,))
        self._sample1 = jax.jit(sample1)
        self._chunk = jax.jit(chunk, static_argnums=(8, 9),
                              donate_argnums=(1,))

    def _init_cache(self):
        """Zero cache for the full batch, built by tracing one dummy step
        (gives the exact per-layer cache structure at [max_batch, ...])."""
        import jax
        import jax.numpy as jnp

        b = self.max_batch
        toks = jnp.zeros((b, 1), jnp.int32)
        positions = jnp.zeros((b, 1), jnp.int32)
        shapes = jax.eval_shape(
            lambda p, t, pos: self.model.apply(
                {"params": p}, t, positions=pos, decode=True,
                mutable=["cache"])[1]["cache"],
            self.params, toks, positions)
        cache = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            # KV-head axis over tp, matching the attention head sharding.
            def _spec(leaf):
                if leaf.ndim == 4:  # [B, S, KV, D]
                    return NamedSharding(self.mesh, P(None, None, "tp", None))
                return NamedSharding(self.mesh, P())

            cache = jax.tree.map(
                lambda leaf: jax.device_put(leaf, _spec(leaf)), cache)
        return cache

    # -------------------------------------------------------------- public
    def submit(self, prompt_tokens, sampling: Optional[SamplingParams] = None
               ) -> GenStream:
        """Queue one request; returns its token stream immediately."""
        sampling = sampling or SamplingParams()
        prompt = np.asarray(prompt_tokens, np.int32).reshape(-1)
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        if len(prompt) + sampling.max_tokens > self.cfg.max_seq:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_tokens ({sampling.max_tokens}) "
                f"exceeds max_seq ({self.cfg.max_seq})")
        stream = GenStream(next(self._req_counter), len(prompt))
        if _tracing.enabled():
            stream.trace = _tracing.current()
        # The _running check and the enqueue must be ONE atomic step
        # against shutdown()'s flag flip: a submit that slips between the
        # check and the put could otherwise queue a stream after the
        # scheduler's final drain — stranding it without _DONE forever.
        with self._lock:
            if not self._running:
                raise RuntimeError("engine is shut down")
            self._pending.put((prompt, sampling, stream))
            self._lock.notify_all()
        return stream

    def generate(self, prompts, sampling: Optional[SamplingParams] = None
                 ) -> list[list[int]]:
        """Batch convenience: submit all, drain all."""
        streams = [self.submit(p, sampling) for p in prompts]
        return [s.tokens() for s in streams]

    def shutdown(self):
        with self._lock:
            self._running = False
            self._lock.notify_all()
        self._thread.join(timeout=10)
        # Belt and braces after the join: the scheduler thread drains
        # _pending on exit, but if the join timed out (thread wedged in a
        # device call) any queued streams would hang their consumers —
        # terminate them here. Safe against the loop's own drain (done
        # markers are idempotent) because no new submit can enqueue after
        # the flag flipped under the lock.
        while True:
            try:
                _p, _s, stream = self._pending.get_nowait()
            except queue.Empty:
                break
            stream._q.put(GenStream._DONE)

    @property
    def num_active(self) -> int:
        return self._n_active

    # ----------------------------------------------------------- scheduler
    def _bucket(self, plen: int) -> int:
        b = 8
        while b < plen:
            b *= 2
        return min(b, self.cfg.max_seq)

    def _admit_async(self, slot: int, prompt, sampling, stream):
        """Dispatch prefill + first-token sample + cache place for one slot
        WITHOUT reading the result back (the caller batches the host reads
        of a whole admission wave into one device sync — each read is a
        full round trip on tunneled/remote TPUs)."""
        import jax.numpy as jnp

        plen = len(prompt)
        lb = self._bucket(plen)
        toks = np.zeros((1, lb), np.int32)
        toks[0, :plen] = prompt
        if self._cache is None:
            self._cache = self._init_cache()
        last_logits, cache_slice = self._prefill(
            self.params, jnp.asarray(toks), plen)
        key = self._jax.random.fold_in(
            self._jax.random.PRNGKey(sampling.seed), stream.request_id)
        first = self._sample1(
            last_logits, key,
            jnp.float32(sampling.temperature),
            jnp.int32(sampling.top_k), jnp.float32(sampling.top_p))
        self._cache = self._place(self._cache, cache_slice,
                                  self._jnp.int32(slot))
        st = _Slot(stream, sampling)
        self._slots[slot] = st
        self._n_active += 1
        self._lengths[slot] = plen
        self._pending_toks[slot] = 0
        self._temps_dev = self._temps_dev.at[slot].set(sampling.temperature)
        self._topks_dev = self._topks_dev.at[slot].set(sampling.top_k)
        self._topps_dev = self._topps_dev.at[slot].set(sampling.top_p)
        self._keys = self._keys.at[slot].set(self._jax.random.fold_in(
            key, 1))
        return first  # device scalar

    def _emit(self, slot: int, tok: int):
        st = self._slots[slot]
        if st.stream.closed:
            st.stream.finish_reason = "cancelled"
            self._retire(slot)
            return
        st.stream._q.put(int(tok))
        st.emitted += 1
        st.remaining -= 1
        stop = st.sampling.stop_token
        if st.remaining <= 0 or (stop is not None and tok == stop):
            st.stream.finish_reason = (
                "stop" if (stop is not None and tok == stop) else "length")
            self._retire(slot)

    def _retire(self, slot: int):
        st = self._slots[slot]
        st.stream._q.put(GenStream._DONE)
        self._slots[slot] = None
        self._n_active -= 1
        self._lengths[slot] = 0
        self._next_tok[slot] = 0
        # (device-side sampling mirrors keep stale values for retired
        # slots; the slot decodes garbage that emit discards)
        if self._q_chunks and slot in self._q_chunks[-1][1]:
            # Already-dispatched chunks still step this slot; it must not
            # be re-admitted until the NEWEST of them is emitted (device
            # program order makes the cache safe — this guards only the
            # host-side slot bookkeeping).
            self._cooling[slot] = self._q_chunks[-1][3]

    def _loop(self):
        """Scheduler with depth-D software pipelining. Host syncs are the
        scarce resource (a tunneled/remote TPU pays ~100ms per blocking
        read): up to `pipeline_depth` decode chunks stay in flight with
        their inputs chained ENTIRELY on device (next-token/length mirrors
        ride chunk outputs, so steady-state dispatch transfers nothing),
        and token readbacks happen one chunk per iteration — each read
        overlaps the execution of every younger in-flight chunk.
        Correctness leans on device program order (place/chunk chain
        through the cache handle); the host only avoids re-admitting a
        slot an in-flight chunk still steps (the _cooling set)."""
        import jax.numpy as jnp

        while self._running:
            # ---- 1. admissions (batched: ONE device sync per wave)
            admits = []
            while (self._n_active + len(admits)) < self.max_batch:
                free = next((i for i, s in enumerate(self._slots)
                             if s is None and i not in self._cooling
                             and all(i != a[0] for a in admits)), None)
                if free is None:
                    break
                try:
                    prompt, sampling, stream = self._pending.get_nowait()
                except queue.Empty:
                    break
                try:
                    t_adm = time.time()
                    first_dev = self._admit_async(free, prompt, sampling,
                                                  stream)
                    _tracing.record_span_in(
                        stream.trace, "engine.prefill", "engine", t_adm,
                        time.time(),
                        {"slot": free, "prompt_len": len(prompt)})
                    admits.append((free, first_dev))
                    # Merge into the device mirrors without a sync.
                    self._toks_dev = self._toks_dev.at[free].set(first_dev)
                    self._lens_dev = self._lens_dev.at[free].set(
                        int(self._lengths[free]))
                except Exception as e:  # bad request or engine failure
                    stream._q.put(e)
                    stream._q.put(GenStream._DONE)
            # First tokens are NOT read here: they join the next drain's
            # single sync (an admission-wave readback would cost its own
            # ~100ms round trip on tunneled TPUs).
            self._pending_firsts.extend(admits)
            if self._n_active == 0 and not self._q_chunks:
                with self._lock:
                    if self._pending.empty() and self._running:
                        self._lock.wait(timeout=0.1)
                continue
            # ---- 2. fill the pipeline: dispatch up to pipeline_depth
            # chunks back to back (dispatches are asynchronous and nearly
            # free; only the readback costs a round trip)
            while len(self._q_chunks) < self.pipeline_depth:
                active = [i for i, s in enumerate(self._slots)
                          if s is not None]
                if not active:
                    break
                budget = int(min(
                    min(self._slots[i].remaining - self._pending_toks[i]
                        for i in active),
                    min(self.cfg.max_seq - int(self._lengths[i])
                        for i in active)))
                if budget < 1:
                    break  # every active slot's fate is already in flight
                # Power-of-2 chunk sizes only: each distinct scan length
                # is its own compiled program, and an arbitrary shrinking
                # budget would recompile on nearly every call.
                n = max(1, min(self.decode_chunk,
                               1 << (budget.bit_length() - 1)))
                greedy = all(
                    self._slots[i].sampling.temperature <= 0.0
                    for i in active)
                # Per-iteration tracing (README "Tracing & timeline"): bind
                # the decode loop's spans to the oldest active TRACED
                # request — in the one-request case (the BENCH_r05 gap's
                # shape) every dispatch and host sync lands in its timeline.
                tctx = next((self._slots[i].stream.trace for i in active
                             if self._slots[i].stream.trace is not None),
                            None)
                try:
                    t_disp = time.time()
                    self._cache, self._keys, toks_out, lens_out = \
                        self._chunk(
                            self.params, self._cache,
                            self._toks_dev, self._lens_dev,
                            self._keys, self._temps_dev,
                            self._topks_dev, self._topps_dev, n, greedy)
                    _tracing.record_span_in(
                        tctx, "engine.dispatch_chunk", "engine", t_disp,
                        time.time(), {"tokens": n, "active": len(active)})
                    # Chain on device; mirror lengths on host (every slot
                    # steps n times — deterministic, no read needed).
                    self._toks_dev = toks_out[:, n - 1]
                    self._lens_dev = lens_out
                    self._lengths = self._lengths + n
                    for i in active:
                        self._pending_toks[i] += n
                    self._q_chunks.append((toks_out, active, n, object()))
                except Exception as e:
                    logger.exception("llm engine decode chunk failed")
                    for i in active:
                        self._slots[i].stream._q.put(e)
                        self._retire(i)
                    break
            # ---- 3. drain: read the admission wave's first tokens AND
            # every queued chunk in ONE device sync (a concatenated
            # transfer costs the same round trip as one chunk's worth)
            if self._q_chunks or self._pending_firsts:
                q, self._q_chunks = self._q_chunks, []
                firsts, self._pending_firsts = self._pending_firsts, []
                parts = []
                if firsts:
                    col = jnp.zeros((self.max_batch, 1), jnp.int32)
                    for slot, fdev in firsts:
                        col = col.at[slot, 0].set(fdev)
                    parts.append(col)
                parts.extend(c[0] for c in q)
                # The host-sync readback: THE per-iteration host-link round
                # trip the decode loop pays (the 22x end-to-end gap in
                # BENCH_r05 is made of these). Span it against the oldest
                # traced in-flight request + the decode-step histogram.
                sync_ctx = None
                if _tracing.enabled():
                    sync_ctx = next(
                        (self._slots[i].stream.trace
                         for _t, p_active, _n, _tag in q for i in p_active
                         if self._slots[i] is not None
                         and self._slots[i].stream.trace is not None),
                        None)
                    if sync_ctx is None:
                        sync_ctx = next(
                            (self._slots[s].stream.trace
                             for s, _f in firsts
                             if self._slots[s] is not None
                             and self._slots[s].stream.trace is not None),
                            None)
                t_sync = time.time()
                try:
                    all_np = np.asarray(
                        parts[0] if len(parts) == 1
                        else jnp.concatenate(parts, axis=1))
                except Exception as e:
                    for slot, _f in firsts:
                        if self._slots[slot] is not None:
                            self._slots[slot].stream._q.put(e)
                            self._retire(slot)
                    for _t, p_active, _n, _tag in q:
                        for i in p_active:
                            if self._slots[i] is not None:
                                self._slots[i].stream._q.put(e)
                                self._retire(i)
                    all_np = None
                if sync_ctx is not None and all_np is not None:
                    t_end = time.time()
                    _tracing.record_span_in(
                        sync_ctx, "engine.host_sync", "engine", t_sync,
                        t_end, {"chunks": len(q),
                                "cols": int(all_np.shape[1])})
                    try:
                        from ray_tpu.util import metrics as _metrics

                        _metrics.DECODE_STEP_SECONDS.observe(t_end - t_sync)
                    except Exception:
                        pass
                off = 0
                if firsts and all_np is not None:
                    for slot, _f in firsts:
                        if self._slots[slot] is None:
                            continue  # retired by a failed-dispatch path
                        self._next_tok[slot] = int(all_np[slot, 0])
                        self._emit(slot, int(all_np[slot, 0]))
                if firsts:
                    off = 1
                for _toks_dev, p_active, pn, tag in q:
                    if all_np is not None:
                        for i in p_active:
                            self._pending_toks[i] = max(
                                0, self._pending_toks[i] - pn)
                            if self._slots[i] is None:
                                continue  # retired; tail is garbage
                            for j in range(off, off + pn):
                                if self._slots[i] is None:
                                    break
                                self._emit(i, int(all_np[i, j]))
                            if self._slots[i] is not None:
                                self._next_tok[i] = int(
                                    all_np[i, off + pn - 1])
                    off += pn
                    self._cooling = {s: t for s, t in self._cooling.items()
                                     if t is not tag}
        # drain on shutdown
        for i, s in enumerate(self._slots):
            if s is not None:
                s.stream._q.put(GenStream._DONE)
        while True:
            try:
                _p, _s, stream = self._pending.get_nowait()
            except queue.Empty:
                break
            stream._q.put(GenStream._DONE)
