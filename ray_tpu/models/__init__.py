"""Model zoo: flax models designed mesh-first.

Every model ships with a `param_specs` giving the PartitionSpec tree for its
parameters (dp/fsdp/tp/sp axes), so trainers shard by annotation and XLA
inserts the collectives — the GSPMD replacement for the reference's
DDP/FSDP/vLLM-TP delegation (train/torch/config.py:36, vllm_models.py:123).
"""

from ray_tpu.models.transformer import Transformer, TransformerConfig
from ray_tpu.models.mlp import MLP

__all__ = ["Transformer", "TransformerConfig", "MLP"]
