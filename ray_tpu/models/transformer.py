"""Flagship model: llama-style decoder transformer, mesh-first.

TPU-native design notes:
- bfloat16 activations / f32 params & optimizer state (MXU-friendly).
- Megatron-style sharding via PartitionSpecs (param_specs): attention and
  MLP matmuls split over "tp", parameters additionally over "fsdp"
  (ZeRO-3 analogue), activations between blocks sequence-sharded over "sp";
  XLA/GSPMD inserts the all-gathers/reduce-scatters over ICI.
- Attention goes through ray_tpu.ops.dot_product_attention (Pallas flash
  kernel on TPU, XLA reference elsewhere).
- The reference framework has no model zoo of its own — this fills the role
  its vLLM/torch delegation played (llm/_internal/serve/.../vllm_models.py
  TP/PP passthrough), natively.
"""

from __future__ import annotations

from dataclasses import dataclass

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ray_tpu.ops import dot_product_attention


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8  # < n_heads => GQA
    d_ff: int = 1376  # ~8/3 * d_model, SwiGLU
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: jnp.dtype = jnp.bfloat16  # activation/compute dtype
    param_dtype: jnp.dtype = jnp.float32
    #: >0 switches the MLP to a top-2 MoE with this many experts, sharded
    #: over the "ep" mesh axis.
    moe_experts: int = 0

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def _rope(x, positions, theta: float):
    """Rotary position embeddings. x: [B, S, H, D], positions: [B, S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", nn.initializers.ones, (x.shape[-1],), jnp.float32)
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (norm * scale).astype(x.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, decode: bool = False):
        cfg = self.cfg
        hd = cfg.head_dim
        dense = lambda feats, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, name=name,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        q = dense((cfg.n_heads, hd), "wq")(x)
        k = dense((cfg.n_kv_heads, hd), "wk")(x)
        v = dense((cfg.n_kv_heads, hd), "wv")(x)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        if decode:
            out = self._cached_attention(q, k, v, positions)
        else:
            out = dot_product_attention(q, k, v, causal=True)
        return nn.DenseGeneral(cfg.d_model, axis=(-2, -1), use_bias=False, name="wo",
                               dtype=cfg.dtype, param_dtype=cfg.param_dtype)(out)

    def _cached_attention(self, q, k, v, positions):
        """Autoregressive KV-cache attention with PER-SEQUENCE positions
        (reference role: vLLM's paged KV cache; here slot-per-sequence):
        new k/v rows scatter into fixed [B, max_seq, KV, D] buffers at each
        sequence's own absolute positions, so one compiled step can serve a
        continuous batch whose members are at different depths (the
        requirement of in-flight batching). Visibility for query i of
        sequence b is t <= positions[b, i]; rows above a sequence's current
        position are never visible, so stale pad/previous-request garbage
        in the slot can never leak into attention. Single-token steps
        (S==1, the serving hot loop) use the Pallas decode kernel
        (ops/decode_attention.py)."""
        cfg = self.cfg
        b, s = q.shape[0], q.shape[1]
        ck = self.variable("cache", "k", lambda: jnp.zeros(
            (b, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim), cfg.dtype))
        cv = self.variable("cache", "v", lambda: jnp.zeros(
            (b, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim), cfg.dtype))
        pos = positions.astype(jnp.int32)
        bidx = jnp.arange(b)[:, None]
        ck.value = ck.value.at[bidx, pos].set(k.astype(cfg.dtype))
        cv.value = cv.value.at[bidx, pos].set(v.astype(cfg.dtype))
        keys, vals = ck.value, cv.value
        if s == 1:
            from ray_tpu.ops.decode_attention import decode_attention

            out = decode_attention(q[:, 0], keys, vals, pos[:, 0] + 1)
            return out[:, None].astype(cfg.dtype)
        if cfg.n_kv_heads < cfg.n_heads:  # GQA: broadcast kv heads
            rep = cfg.n_heads // cfg.n_kv_heads
            keys = jnp.repeat(keys, rep, axis=2)
            vals = jnp.repeat(vals, rep, axis=2)
        scores = jnp.einsum("bshd,bthd->bhst", q.astype(jnp.float32),
                            keys.astype(jnp.float32)) / (cfg.head_dim ** 0.5)
        # cache row t is visible to query i of sequence b iff t <= pos[b, i]
        t_pos = jnp.arange(cfg.max_seq)[None, None, None, :]
        q_pos = pos[:, None, :, None]
        scores = jnp.where(t_pos <= q_pos, scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhst,bthd->bshd", probs,
                         vals.astype(jnp.float32))
        return out.astype(cfg.dtype)


class SwiGLU(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dense = lambda feats, name: nn.Dense(  # noqa: E731
            feats, use_bias=False, name=name, dtype=cfg.dtype, param_dtype=cfg.param_dtype)
        gate = nn.silu(dense(cfg.d_ff, "w_gate")(x))
        up = dense(cfg.d_ff, "w_up")(x)
        return dense(cfg.d_model, "w_down")(gate * up)


class MoE(nn.Module):
    """Top-2 mixture-of-experts SwiGLU, expert-parallel over "ep".

    Expert weights carry a leading [E] axis sharded over the ep mesh axis;
    each device computes its expert shard over all tokens and the combine
    contraction reduces over ep (XLA inserts the collective). Dense
    dispatch (no capacity/dropping) keeps the math exactly equal to the
    single-device reference — the routing SEMANTICS and the ep sharding are
    what the dryrun proves; capacity-based all_to_all dispatch is the
    optimization seam."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e, dm, ff = cfg.moe_experts, cfg.d_model, cfg.d_ff
        router = self.param("router", nn.initializers.normal(0.02),
                            (dm, e), jnp.float32)
        w_gate = self.param("w_gate", nn.initializers.lecun_normal(),
                            (e, dm, ff), cfg.param_dtype)
        w_up = self.param("w_up", nn.initializers.lecun_normal(),
                          (e, dm, ff), cfg.param_dtype)
        w_down = self.param("w_down", nn.initializers.lecun_normal(),
                            (e, ff, dm), cfg.param_dtype)
        logits = x.astype(jnp.float32) @ router  # [B, S, E]
        probs = jax.nn.softmax(logits, axis=-1)
        k = min(2, e)  # top-2 routing (top-1 when only one expert)
        kth = jax.lax.top_k(probs, k)[0][..., -1:]  # k-th highest prob
        gates = jnp.where(probs >= kth, probs, 0.0)
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)  # renorm top-k
        xc = x.astype(cfg.dtype)
        gate_h = nn.silu(jnp.einsum("bsd,edf->ebsf", xc, w_gate.astype(cfg.dtype)))
        up_h = jnp.einsum("bsd,edf->ebsf", xc, w_up.astype(cfg.dtype))
        expert_out = jnp.einsum("ebsf,efd->ebsd", gate_h * up_h,
                                w_down.astype(cfg.dtype))
        return jnp.einsum("ebsd,bse->bsd", expert_out,
                          gates.astype(cfg.dtype))


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, positions, decode: bool = False):
        x = x + Attention(self.cfg, name="attn")(
            RMSNorm(name="attn_norm")(x), positions, decode=decode)
        mlp = (MoE(self.cfg, name="moe") if self.cfg.moe_experts
               else SwiGLU(self.cfg, name="mlp"))
        x = x + mlp(RMSNorm(name="mlp_norm")(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, positions=None, decode: bool = False):
        """tokens: [B, S] int32 -> logits [B, S, vocab] (f32).

        decode=True uses per-layer KV caches (flax "cache" collection):
        pass `positions` (absolute) and apply with mutable=["cache"]."""
        cfg = self.cfg
        emb = self.param("tok_emb", nn.initializers.normal(0.02),
                         (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        x = emb[tokens].astype(cfg.dtype)
        if positions is None:
            positions = jnp.broadcast_to(jnp.arange(tokens.shape[1]), tokens.shape)
        for i in range(cfg.n_layers):
            if not decode:
                x = _seq_shard(x)
            x = Block(cfg, name=f"layer_{i}")(x, positions, decode=decode)
        x = RMSNorm(name="final_norm")(x)
        # Tied output head (vocab-sharded matmul over tp).
        return jnp.einsum("bsd,vd->bsv", x, emb.astype(cfg.dtype)).astype(jnp.float32)


def _seq_shard(x):
    """Sequence-parallel activation constraint between blocks: [B, S, D]
    sharded batch over (dp, fsdp) and sequence over sp. GSPMD gathers the
    sequence inside attention (Megatron-SP style); ring attention
    (ray_tpu/ops/ring_attention.py) removes that gather when enabled."""
    try:
        return jax.lax.with_sharding_constraint(x, P(("dp", "fsdp"), "sp", None))
    except Exception:
        return x  # not under a mesh (single-device tests)


def param_specs(params) -> dict:
    """PartitionSpec tree matching init(params): Megatron TP + fsdp sharding.

    kernels are [in, out] (flax Dense); DenseGeneral qkv kernels are
    [d_model, heads, head_dim]; wo kernel is [heads, head_dim, d_model].
    """

    def rule(path: tuple[str, ...], leaf):
        last = path[-1]
        name = path[-2] if len(path) >= 2 else last
        moe = "moe" in path
        if last == "tok_emb":
            return P("tp", "fsdp")  # vocab over tp, d_model over fsdp
        if last == "router":
            return P("fsdp", None)
        if moe and last in ("w_gate", "w_up"):
            return P("ep", "fsdp", "tp")  # leading [E] axis over ep
        if moe and last == "w_down":
            return P("ep", "tp", "fsdp")
        if name in ("wq", "wk", "wv"):
            return P("fsdp", "tp", None)  # heads over tp
        if name == "wo":
            return P("tp", None, "fsdp")
        if name in ("w_gate", "w_up"):
            return P("fsdp", "tp")
        if name == "w_down":
            return P("tp", "fsdp")
        return P()  # norms etc: replicated

    from ray_tpu.parallel.mesh import spec_tree_like

    return spec_tree_like(params, rule)


def loss_fn(model: Transformer, params, tokens):
    """Next-token cross entropy, mean over all positions."""
    logits = model.apply(params, tokens[:, :-1])
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()
