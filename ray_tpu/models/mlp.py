"""Small MLP (MNIST-class) — the minimum end-to-end training model
(SURVEY §7 stage 4 / BASELINE north-star #1: DataParallelTrainer MNIST)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class MLP(nn.Module):
    hidden: int = 128
    n_classes: int = 10

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(self.hidden)(x))
        x = nn.relu(nn.Dense(self.hidden)(x))
        return nn.Dense(self.n_classes)(x)


def loss_fn(model: MLP, params, batch):
    x, y = batch
    logits = model.apply(params, x)
    logp = jnp.take_along_axis(nn.log_softmax(logits), y[:, None], axis=-1)
    return -logp.mean()
