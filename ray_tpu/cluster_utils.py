"""Multi-node cluster-in-one-machine test harness.

Parity target: reference python/ray/cluster_utils.py:135 (Cluster — the
load-bearing mechanism for multi-node testing: `add_node()` spawns real
raylets with fake resources on one machine; cf. SURVEY §4). Here each
`add_node` spawns a real NodeAgent subprocess with declared (fake) resources;
workers/actors/objects behave exactly as on a real multi-host cluster, modulo
shared /dev/shm (same as the reference's shared plasma on one box).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from ray_tpu._private import rpc
from ray_tpu._private.bootstrap import HeadNode
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import ResourceSet


class _NodeHandle:
    def __init__(self, node_id: str, proc: subprocess.Popen):
        self.node_id = node_id
        self.proc = proc


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: dict | None = None):
        args = dict(head_node_args or {})
        args.setdefault("num_cpus", 1)
        self.head = HeadNode(**args)
        self.controller_addr = self.head.start()
        self.nodes: list[_NodeHandle] = []
        self._io = rpc.EventLoopThread(name="cluster-util")
        self._conn: rpc.Connection | None = None

    @property
    def address(self) -> str:
        return f"{self.controller_addr[0]}:{self.controller_addr[1]}"

    def _call(self, method: str, **kw):
        async def _go():
            global_conn = self._conn
            if global_conn is None or global_conn.closed:
                self._conn = await rpc.connect(*self.controller_addr)
                await self._conn.call("register", kind="client", worker_id="cluster-util", address=None)
            return await self._conn.call(method, **kw)

        return self._io.run(_go(), timeout=30)

    def add_node(
        self,
        num_cpus: float = 1,
        num_tpus: float = 0,
        resources: dict | None = None,
        labels: dict | None = None,
        env: dict | None = None,
    ) -> _NodeHandle:
        node_id = NodeID.from_random().hex()
        res = {"CPU": float(num_cpus)}
        if num_tpus:
            res["TPU"] = float(num_tpus)
        res.update(resources or {})
        penv = dict(os.environ)
        penv.update(env or {})
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        # Forward the driver's sys.path (like HeadNode does for the local
        # node): workers on this node must unpickle by-reference functions
        # from any module the driver can import. Explicit PYTHONPATH stays
        # first so it can shadow inherited driver paths.
        driver_paths = [p for p in sys.path if p and os.path.exists(p)]
        existing = penv.get("PYTHONPATH", "")
        penv["PYTHONPATH"] = os.pathsep.join(
            ([existing] if existing else []) + [pkg_root] + driver_paths)
        proc = subprocess.Popen(
            [
                sys.executable,
                "-m",
                "ray_tpu._private.node_agent",
                "--controller",
                self.address,
                "--node-id",
                node_id,
                "--session",
                self.head.session_id,
                "--resources",
                json.dumps(ResourceSet(res).raw()),
                "--labels",
                json.dumps(labels or {}),
            ],
            env=penv,
        )
        handle = _NodeHandle(node_id, proc)
        self.nodes.append(handle)
        self._wait_node_state(node_id, alive=True)
        return handle

    def remove_node(self, node: _NodeHandle, allow_graceful: bool = False):
        node.proc.kill()
        node.proc.wait(timeout=10)
        try:
            # Explicit removal: skip the liveness suspicion grace window
            # (the kill is a fact, not a blip) so dependent failure
            # handling (actor restarts, object loss) runs immediately.
            self._call("kill_node", node_id=node.node_id)
        except Exception:
            pass
        self._wait_node_state(node.node_id, alive=False)
        self.nodes.remove(node)

    def _wait_node_state(self, node_id: str, alive: bool, timeout: float = 30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            snap = self._call("state_snapshot")
            ent = snap["nodes"].get(node_id)
            if ent is not None and ent["alive"] == alive:
                return
            time.sleep(0.05)
        raise TimeoutError(f"node {node_id[:8]} did not become alive={alive}")

    def wait_for_nodes(self, timeout: float = 30.0):
        for n in self.nodes:
            self._wait_node_state(n.node_id, alive=True, timeout=timeout)

    def shutdown(self):
        for n in list(self.nodes):
            try:
                n.proc.kill()
            except Exception:
                pass
        self._io.stop()
        self.head.stop()
