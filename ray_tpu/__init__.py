"""ray_tpu — a TPU-native distributed AI runtime.

Public API parity target: reference python/ray/_private/worker.py
(init:1286, shutdown:1931, get:2718, put:2854, wait:2919, remote:3407).
"""

from __future__ import annotations

import atexit
import logging
import os
import threading
from typing import Any, Iterable, Sequence

from ray_tpu import exceptions  # noqa: F401
from ray_tpu._private.bootstrap import HeadNode
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu._private.worker import (
    ObjectRef,
    ObjectRefGenerator,
    Worker,
    global_worker,
    set_global_worker,
)
from ray_tpu.actor import ActorClass, ActorHandle, get_actor, kill, method  # noqa: F401
from ray_tpu.remote_function import RemoteFunction

__version__ = "0.1.0"

logger = logging.getLogger(__name__)

_head: HeadNode | None = None
_init_lock = threading.Lock()
_config_baseline: dict | None = None


def is_initialized() -> bool:
    return global_worker() is not None


def init(
    address: str | None = None,
    *,
    num_cpus: float | None = None,
    num_tpus: float | None = None,
    resources: dict[str, float] | None = None,
    labels: dict[str, str] | None = None,
    namespace: str = "default",
    runtime_env: dict | None = None,
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    _system_config: dict | None = None,
    _worker_env: dict | None = None,
):
    """Start (or connect to) a cluster and attach this process as the driver.

    With no `address`, brings up an in-process head (controller + node agent,
    cf. reference node.py:1437 start_head_processes) and a worker pool of
    subprocesses. With `address="host:port"`, connects to a running cluster
    (started via `ray-tpu start --head`).
    """
    global _head
    with _init_lock:
        if global_worker() is not None:
            if ignore_reinit_error:
                return
            raise RuntimeError("ray_tpu.init() called twice; use shutdown() first.")
        global _config_baseline
        # Save the OVERRIDE table, not the resolved values: restoring the
        # full resolved snapshot would freeze every flag as an override and
        # silently disable RT_* env resolution for the rest of the process.
        _config_baseline = dict(CONFIG._overrides)
        CONFIG.apply_system_config(_system_config)
        if CONFIG.fault_injection:
            # Chaos-test gate: must flip on BEFORE the head/agent/worker
            # connections are created so the injector tracks them.
            from ray_tpu._private import rpc as _rpc

            _rpc.enable_fault_injection()
        if address is None:
            # Submitted jobs inherit the cluster address from their runner
            # (reference: RAY_ADDRESS set by the job supervisor).
            address = os.environ.get("RT_ADDRESS") or None
        if address is None:
            _head = HeadNode(
                num_cpus=num_cpus,
                num_tpus=num_tpus,
                resources=resources,
                labels=labels,
                worker_env=_worker_env,
            )
            controller_addr = _head.start()
            session_id = _head.session_id
        else:
            host, port = address.rsplit(":", 1)
            controller_addr = (host, int(port))
            # Session id is learned from the controller at register time.
            session_id = "remote"
        w = Worker(mode="driver", session_id=session_id, controller_addr=controller_addr)
        w.connect()
        if address is not None:
            # Adopt the cluster's session id for the shared shm namespace.
            rep = w.io.run(w.controller.call("ping"))
            w.session_id = rep["session_id"]
            w.store.session = rep["session_id"][:8]
        w.namespace = namespace
        if log_to_driver:
            try:
                w.io.run(w.controller.call("subscribe_logs", on=True), timeout=10)
            except Exception:
                pass
        set_global_worker(w)
        atexit.register(shutdown)
        return w


def shutdown():
    global _head, _config_baseline
    w = global_worker()
    if w is not None:
        w.disconnect()
    if _head is not None:
        _head.stop()
        _head = None
    # Session-scoped fault injection dies with the session (env-gated
    # injection is process-scoped and stays): stale rules must not apply
    # to a later init() that never asked for injection.
    if CONFIG.fault_injection and not os.environ.get("RT_FAULT_INJECTION"):
        from ray_tpu._private import rpc as _rpc

        _rpc.disable_fault_injection()
    # _system_config overrides are session-scoped: restore the pre-init
    # override table so the next init() in this process starts clean.
    if _config_baseline is not None:
        try:
            CONFIG._overrides.clear()
            CONFIG._overrides.update(_config_baseline)
            # The cluster snapshot received at registration is session
            # state too: a later init() against a different cluster must
            # not inherit this one's resolved table.
            CONFIG._snapshot.clear()
        except Exception:
            pass
        _config_baseline = None
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass


def _require_worker() -> Worker:
    w = global_worker()
    if w is None:
        raise RuntimeError("ray_tpu.init() has not been called.")
    return w


def remote(*args, **options):
    """@remote decorator for functions and classes (reference worker.py:3407)."""
    import inspect

    def decorate(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, options)
        return RemoteFunction(obj, options)

    if len(args) == 1 and not options and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        return decorate(args[0])
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_cpus=2)")
    return decorate


def put(value) -> ObjectRef:
    return _require_worker().put(value)


def get(refs, timeout: float | None = None):
    w = _require_worker()
    if isinstance(refs, ObjectRef):
        return w.get([refs], timeout=timeout)[0]
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list must contain only ObjectRefs, got {type(r)}")
    return w.get(list(refs), timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: float | None = None,
    fetch_local: bool = True,
):
    w = _require_worker()
    if isinstance(refs, ObjectRef):
        raise TypeError("wait() expects a list of ObjectRefs")
    return w.wait(list(refs), num_returns=num_returns, timeout=timeout)


def cancel(ref, *, force: bool = False):
    """Cancel a queued or running task (reference ray.cancel,
    core_worker.proto:492 CancelTask). Non-force delivers KeyboardInterrupt
    to the executing worker and get() raises TaskCancelledError; force kills
    the worker process and get() raises WorkerCrashedError. Child tasks are
    not cancelled recursively. Accepts an ObjectRefGenerator to cancel a
    streaming task mid-stream."""
    w = _require_worker()
    if isinstance(ref, ObjectRefGenerator):
        return w.cancel_task(ref.task_id, force)
    return w.cancel_task(ref.task_id(), force)


def cluster_resources() -> dict[str, float]:
    return _require_worker().cluster_resources()["total"]


def available_resources() -> dict[str, float]:
    return _require_worker().cluster_resources()["available"]


def nodes() -> list[dict]:
    snap = _require_worker().state_snapshot()
    return [
        {"NodeID": nid, "Alive": n["alive"], "Resources": n["total"], "Labels": n["labels"]}
        for nid, n in snap["nodes"].items()
    ]


def timeline(filename: str | None = None) -> list[dict]:
    """Chrome-trace task timeline (reference ray.timeline(),
    _private/state.py:965): complete "X" events per task execution plus
    process/thread name metadata — opens directly in Perfetto /
    chrome://tracing. Pass filename to also write the JSON file."""
    w = _require_worker()
    rep = w.io.run(w.controller.call("get_task_events"), timeout=30)
    events = rep["events"]
    node_pid: dict[str, int] = {}
    trace: list[dict] = []
    seen_threads: set[tuple[int, int]] = set()
    for ev in events:
        pid = node_pid.setdefault(ev["node_id"], len(node_pid) + 1)
        tid = int(ev["pid"])
        if (pid, 0) not in seen_threads:
            seen_threads.add((pid, 0))
            trace.append({"ph": "M", "name": "process_name", "pid": pid,
                          "args": {"name": f"node {ev['node_id'][:8]}"}})
        if (pid, tid) not in seen_threads:
            seen_threads.add((pid, tid))
            trace.append({"ph": "M", "name": "thread_name", "pid": pid,
                          "tid": tid,
                          "args": {"name": f"worker {ev['worker_id'][:8]}"}})
        trace.append({
            "ph": "X",
            "name": ev["name"],
            "cat": ev["kind"],
            "pid": pid,
            "tid": tid,
            "ts": ev["start"] * 1e6,
            "dur": max(1.0, (ev["end"] - ev["start"]) * 1e6),
            "args": {"task_id": ev["task_id"], "attempt": ev["attempt"],
                     "ok": ev["ok"]},
        })
    if filename:
        import json as _json

        with open(filename, "w") as f:
            _json.dump(trace, f)
    return trace


__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "put",
    "get",
    "wait",
    "cancel",
    "kill",
    "get_actor",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "cluster_resources",
    "available_resources",
    "nodes",
    "exceptions",
    "__version__",
]
