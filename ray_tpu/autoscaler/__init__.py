"""Autoscaler: demand-driven node scaling.

Parity target: reference autoscaler v2 (python/ray/autoscaler/v2/
autoscaler.py:42 + scheduler.py's demand bin-packing + instance_manager/):
a reconciler loop reads unmet resource demand from the controller, computes
the node delta against a provider's node shape, and launches/terminates
nodes through a pluggable NodeProvider. The bundled LocalNodeProvider
launches real NodeAgent subprocesses on this machine (reference
FakeMultiNodeProvider, autoscaler/_private/fake_multi_node/
node_provider.py:236 — the harness the reference's own autoscaler tests
use).
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import threading
import time
from typing import Optional

from ray_tpu._private import rpc
from ray_tpu._private.ids import NodeID
from ray_tpu._private.resources import ResourceSet

logger = logging.getLogger(__name__)


class NodeProvider:
    """Launches and terminates worker nodes of one shape.

    Reference: python/ray/autoscaler/node_provider.py (create_node,
    terminate_node, non_terminated_nodes) collapsed to the v2 essentials."""

    #: resources each new node contributes, e.g. {"CPU": 4}
    node_shape: dict

    def create_node(self) -> str:
        """Launch one node; returns its node_id."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Subprocess NodeAgents on this machine (testing / single-host)."""

    def __init__(self, address: str, session_id: str,
                 node_shape: Optional[dict] = None,
                 env: Optional[dict] = None):
        self.address = address
        self.session_id = session_id
        self.node_shape = dict(node_shape or {"CPU": 1.0})
        self.env = dict(env or {})
        self._procs: dict[str, subprocess.Popen] = {}

    def create_node(self) -> str:
        node_id = NodeID.from_random().hex()
        penv = dict(os.environ)
        penv.update(self.env)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        driver_paths = [p for p in sys.path if p and os.path.exists(p)]
        existing = penv.get("PYTHONPATH", "")
        penv["PYTHONPATH"] = os.pathsep.join(
            ([existing] if existing else []) + [pkg_root] + driver_paths)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.node_agent",
             "--controller", self.address,
             "--node-id", node_id,
             "--session", self.session_id,
             "--resources", json.dumps(ResourceSet(self.node_shape).raw()),
             "--labels", json.dumps({"autoscaler": "true"})],
            env=penv)
        self._procs[node_id] = proc
        return node_id

    def terminate_node(self, node_id: str) -> None:
        proc = self._procs.pop(node_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> list[str]:
        return [nid for nid, p in self._procs.items() if p.poll() is None]


class Autoscaler:
    """Reconciler: poll demand -> bin-pack against capacity -> scale.

    Scale-up: any demand shape that fits NO alive node's available
    resources (and no pending launch) asks for new nodes, bin-packed onto
    the provider's node shape. Scale-down: autoscaler-launched nodes whose
    resources have been fully idle for `idle_timeout_s` are terminated
    (never below `min_workers`). Reference: v2 Autoscaler._run_once.
    """

    def __init__(self, address: str, provider: NodeProvider,
                 min_workers: int = 0, max_workers: int = 4,
                 idle_timeout_s: float = 30.0, interval_s: float = 1.0):
        self.provider = provider
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._io = rpc.EventLoopThread(name="autoscaler")
        self._conn: Optional[rpc.Connection] = None
        self._idle_since: dict[str, float] = {}
        # node_id -> launch time; in flight until it registers as alive
        # (or 60s passes — a crashed agent must not block scale-up forever).
        self._pending_launch: dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _call(self, method: str, **kw):
        async def _go():
            if self._conn is None or self._conn.closed:
                self._conn = await rpc.connect(*self._addr)
                await self._conn.call("register", kind="client",
                                      worker_id=f"autoscaler-{os.getpid()}",
                                      address=None)
            return await self._conn.call(method, **kw)

        return self._io.run(_go(), timeout=30)

    # ---------------------------------------------------------- lifecycle
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rt-autoscaler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._io.stop()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.run_once()
            except Exception:
                logger.exception("autoscaler iteration failed")

    # --------------------------------------------------------- reconcile
    @staticmethod
    def _fits(shape: dict, avail: dict) -> bool:
        return all(avail.get(k, 0.0) >= v for k, v in shape.items() if v > 0)

    def run_once(self):
        snap = self._call("state_snapshot")
        dem = self._call("resource_demand")
        provider_nodes = set(self.provider.non_terminated_nodes())
        alive = {nid: n for nid, n in snap["nodes"].items() if n["alive"]}
        now = time.monotonic()
        # A launch stops being "in flight" when ITS node registers (keyed by
        # node id — counting alive nodes against a timestamp list miscounts
        # as soon as any node outlives the window), or after 60s.
        self._pending_launch = {
            nid: t for nid, t in self._pending_launch.items()
            if nid not in alive and now - t < 60.0}
        n_inflight = len(self._pending_launch)

        # ---- scale up: demand no alive node can absorb
        avails = [dict(n["available"]) for n in alive.values()]
        unmet: list[dict] = []
        for shape in dem["demand"] + dem["pg_demand"]:
            if not shape:
                continue
            for av in avails:
                if self._fits(shape, av):
                    for k, v in shape.items():
                        av[k] = av.get(k, 0.0) - v  # consume, greedy pack
                    break
            else:
                unmet.append(shape)
        needed = 0
        if unmet:
            # Bin-pack unmet shapes onto fresh provider-shaped nodes.
            bins: list[dict] = []
            for shape in unmet:
                if not self._fits(shape, self.provider.node_shape):
                    continue  # can never fit this node type; skip
                for b in bins:
                    if self._fits(shape, b):
                        for k, v in shape.items():
                            b[k] -= v
                        break
                else:
                    b = dict(self.provider.node_shape)
                    for k, v in shape.items():
                        b[k] = b.get(k, 0.0) - v
                    bins.append(b)
            needed = len(bins)
        current = len(provider_nodes) + n_inflight
        deficit = max(self.min_workers - current, 0)
        to_launch = min(max(needed - n_inflight, deficit),
                        self.max_workers - current)
        for _ in range(max(0, to_launch)):
            nid = self.provider.create_node()
            self._pending_launch[nid] = now
            logger.info("autoscaler: launched node %s (%d in flight)",
                        nid[:8], len(self._pending_launch))

        # ---- scale down: fully-idle autoscaler nodes past the timeout
        if len(provider_nodes) <= self.min_workers:
            return
        for nid in list(provider_nodes):
            n = alive.get(nid)
            if n is None:
                continue
            # Job drivers consume no controller-visible resources; the
            # active_jobs count is the only signal a node is hosting one.
            idle = (n["available"] == n["total"]
                    and not n.get("active_jobs", 0))
            if not idle:
                self._idle_since.pop(nid, None)
                continue
            first = self._idle_since.setdefault(nid, now)
            if (now - first >= self.idle_timeout_s
                    and len(self.provider.non_terminated_nodes()) > self.min_workers):
                # Drain-then-verify: mark the node unschedulable, re-read its
                # state, and only kill it if it is STILL fully idle — work
                # dispatched between our snapshot and now must not die.
                self._call("drain_node", node_id=nid, on=True)
                fresh = self._call("state_snapshot")["nodes"].get(nid)
                if fresh is None or not fresh["alive"] or \
                        fresh["available"] != fresh["total"] or \
                        fresh.get("active_jobs", 0):
                    self._call("drain_node", node_id=nid, on=False)
                    self._idle_since.pop(nid, None)
                    continue
                logger.info("autoscaler: terminating idle node %s", nid[:8])
                self._idle_since.pop(nid, None)
                self.provider.terminate_node(nid)

    def close(self):
        self.stop()
