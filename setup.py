from setuptools import setup, find_packages

setup(
    name="ray-tpu",
    version="0.1.0",
    description="TPU-native distributed AI runtime",
    packages=find_packages(include=["ray_tpu", "ray_tpu.*"]),
    python_requires=">=3.10",
    entry_points={"console_scripts": ["ray-tpu=ray_tpu.scripts.cli:main"]},
)
