import sys

from tools.rtcheck.core import main

if __name__ == "__main__":
    sys.exit(main())
