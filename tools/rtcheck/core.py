"""rtcheck — AST-based invariant checker for the ray_tpu runtime.

The runtime encodes invariants its C++ reference enforces with types and
clang-tidy; a Python rebuild enforces them only by reviewer vigilance.
rtcheck turns the recurring invariant classes into CI-failing passes:

  async-blocking      event-loop hot paths must never block
  wire-schema         compact wire tuples: encoder/decoder arity agreement
                      + back-compat branches on growth
  knob-registry       every RT_* env literal resolves to a registered
                      rtconfig flag (or the bootstrap allowlist), and every
                      registered flag is documented in the README
  lock-discipline     lock acquisition order is acyclic; helper-thread
                      classes don't mutate shared attrs half-locked
  exception-taxonomy  no swallowed bare/overbroad excepts in _private/ hot
                      paths; RPC handlers raise only taxonomy exceptions
  event-kinds         every emit_event kind literal is declared in the
                      events.py KINDS registry (typo'd kinds are
                      unqueryable forever)

Framework pieces here: the Finding model, inline `# rtcheck: disable=<pass>`
suppressions, the checked-in baseline (grandfathered findings), a per-file
content-hash result cache, and the runner/CLI (`python -m tools.rtcheck`,
`ray-tpu lint`).
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import re
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
BASELINE_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "baseline.json")

#: Default analysis roots, repo-relative (the tier-1 gate runs exactly these).
DEFAULT_ROOTS = ("ray_tpu", "tools")

_SUPPRESS_RE = re.compile(r"#\s*rtcheck:\s*disable=([\w\-,\s]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*rtcheck:\s*disable-file=([\w\-,\s]+)")


def _comment_map(source: str) -> dict[int, str]:
    """line -> comment token text, via the tokenizer (so a '#' inside a
    string literal is never mistaken for a comment)."""
    import io
    import tokenize

    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        pass  # ast.parse will surface real syntax problems
    return out


@dataclass
class Finding:
    """One invariant violation at file:line, attributed to a pass id."""

    pass_id: str
    path: str  # repo-relative
    line: int
    message: str
    col: int = 0
    #: 1-based occurrence index among same-keyed findings in one run,
    #: assigned by the runner in deterministic (path, line, pass) order.
    occurrence: int = 1

    @property
    def key(self) -> str:
        """Stable baseline key. The message (not the line) anchors it, so
        unrelated edits above a grandfathered finding don't churn the
        baseline; repeats of one message in one file get an ordinal suffix
        (:2, :3, ...) so baselining the first does NOT grandfather a new
        identical violation added later."""
        digest = hashlib.sha1(self.message.encode()).hexdigest()[:12]
        base = f"{self.pass_id}:{self.path}:{digest}"
        return base if self.occurrence == 1 else f"{base}:{self.occurrence}"

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_id}] {self.message}"

    def to_json(self) -> dict:
        return {"pass": self.pass_id, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "key": self.key}

    @classmethod
    def from_json(cls, d: dict) -> "Finding":
        return cls(pass_id=d["pass"], path=d["path"], line=d["line"],
                   col=d.get("col", 0), message=d["message"])


class FileCtx:
    """Parsed view of one source file handed to every per-file pass."""

    def __init__(self, relpath: str, source: str):
        self.path = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=relpath)
        self.sha = hashlib.sha1(source.encode()).hexdigest()
        #: line -> comment text (the `# ...` token only). Directives are
        #: matched against REAL comments, never string literals — a string
        #: documenting the suppression syntax must not disable the gate.
        self.comments: dict[int, str] = _comment_map(source)
        self._suppressed: dict[int, set[str]] = {}
        self._file_suppressed: set[str] = set()
        for i, ln in self.comments.items():
            if "rtcheck:" not in ln:
                continue
            m = _SUPPRESS_RE.search(ln)
            if m:
                ids = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self._suppressed[i] = ids
            m = _SUPPRESS_FILE_RE.search(ln)
            if m and i <= 10:
                self._file_suppressed |= {
                    p.strip() for p in m.group(1).split(",") if p.strip()}

    def suppressed(self, pass_id: str, line: int) -> bool:
        """A finding is suppressed by `# rtcheck: disable=<pass>` on its own
        line or the line directly above (for multi-line statements, anywhere
        a comment can sit), or file-wide in the first 10 lines."""
        # Hot path (queried per candidate site): match the parsed sets
        # directly, don't rebuild the JSON table.
        if (pass_id in self._file_suppressed
                or "all" in self._file_suppressed):
            return True
        for ln in (line, line - 1):
            ids = self._suppressed.get(ln)
            if ids and (pass_id in ids or "all" in ids):
                return True
        return False

    def suppression_table(self) -> dict:
        """JSON-able suppression map — cached with per-file results so
        finalize (cross-file) findings honor inline suppressions even when
        the file itself came from the cache."""
        return {"file": sorted(self._file_suppressed),
                "lines": {str(k): sorted(v)
                          for k, v in self._suppressed.items()}}


def _suppr_match(table: dict, pass_id: str, line: int) -> bool:
    fids = table.get("file", ())
    if pass_id in fids or "all" in fids:
        return True
    lines = table.get("lines", {})
    for ln in (line, line - 1):
        ids = lines.get(str(ln))
        if ids and (pass_id in ids or "all" in ids):
            return True
    return False


class Pass:
    """Base pass. Per-file analysis returns (findings, facts); facts are
    JSON-serializable extracts that `finalize` joins across files (and that
    the content-hash cache persists, so unchanged files contribute to
    whole-program checks without reparsing)."""

    id: str = ""

    def wants(self, relpath: str) -> bool:
        return True

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        return [], None

    def finalize(self, facts: dict[str, Any],
                 project: "Project") -> list[Finding]:
        return []


class Project:
    """Whole-run context available to finalize passes (repo root access for
    non-Python inputs like the README knob table). `analyzed` is the set of
    repo-relative paths this run actually scanned — finalize passes use it
    to degrade gracefully on restricted-root runs (e.g.
    `rtcheck ray_tpu/serve`) instead of reporting their anchor files as
    missing."""

    def __init__(self, root: str):
        self.root = root
        self.analyzed: set[str] = set()

    def read_text(self, relpath: str) -> Optional[str]:
        try:
            with open(os.path.join(self.root, relpath)) as f:
                return f.read()
        except OSError:
            return None


# --------------------------------------------------------------------- passes
def all_passes() -> list[Pass]:
    from tools.rtcheck.passes import (async_blocking, event_kinds,
                                      exception_taxonomy, knob_registry,
                                      lock_discipline, wire_schema)

    return [async_blocking.AsyncBlockingPass(),
            wire_schema.WireSchemaPass(),
            knob_registry.KnobRegistryPass(),
            lock_discipline.LockDisciplinePass(),
            exception_taxonomy.ExceptionTaxonomyPass(),
            event_kinds.EventKindsPass()]


def _tool_version() -> str:
    """Content hash of the checker itself: editing any pass invalidates
    every cached result."""
    h = hashlib.sha1()
    tool_dir = os.path.dirname(os.path.abspath(__file__))
    for dirpath, _dirs, files in sorted(os.walk(tool_dir)):
        for name in sorted(files):
            if name.endswith(".py"):
                with open(os.path.join(dirpath, name), "rb") as f:
                    h.update(f.read())
    return h.hexdigest()[:16]


# ---------------------------------------------------------------------- cache
def _default_cache_path(root: str) -> str:
    # User-owned cache home, NOT a predictable world-writable /tmp path: a
    # squatted cache dir could feed back empty findings and silently
    # disable the lint gate on shared machines.
    tag = hashlib.sha1(root.encode()).hexdigest()[:12]
    base = (os.environ.get("RTCHECK_CACHE_DIR")
            or os.path.join(
                os.environ.get("XDG_CACHE_HOME")
                or os.path.join(os.path.expanduser("~"), ".cache"),
                "rtcheck"))
    return os.path.join(base, f"cache_{tag}.json")


class ResultCache:
    """Per-file findings+facts keyed by (source sha, tool version). The
    tier-1 gate re-runs rtcheck every time; warm runs must stay well under
    the 10s budget, so unchanged files skip parse AND analysis."""

    def __init__(self, path: str, tool_version: str):
        self.path = path
        self.tool_version = tool_version
        self._entries: dict[str, dict] = {}
        self._seen: set[str] = set()  # keys touched this run
        self._visited_paths: set[str] = set()  # relpaths scanned this run
        self._dirty = False
        try:
            with open(path) as f:
                data = json.load(f)
            if data.get("tool") == tool_version:
                self._entries = data.get("files", {})
        except (OSError, ValueError):
            pass

    def visit(self, relpath: str) -> None:
        self._visited_paths.add(relpath)

    def get(self, key: str) -> Optional[dict]:
        ent = self._entries.get(key)
        if ent is not None:
            self._seen.add(key)
        return ent

    def put(self, key: str, findings: list[Finding],
            facts: dict[str, Any], suppression: dict) -> None:
        self._entries[key] = {
            "findings": [f.to_json() for f in findings],
            "facts": facts,
            "suppress": suppression,
        }
        self._seen.add(key)
        self._dirty = True

    def save(self) -> None:
        # Evict superseded file versions: an unseen key whose relpath WAS
        # scanned this run is a stale (relpath, sha) from an earlier edit —
        # without this the cache grows by one blob per historical version.
        # Entries for paths outside this run's roots stay (still live).
        live = {k: v for k, v in self._entries.items()
                if k in self._seen
                or k.rsplit(":", 1)[0] not in self._visited_paths}
        dropped = len(self._entries) - len(live)
        if not self._dirty and not dropped:
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"tool": self.tool_version, "files": live}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # cache is best-effort; a cold run is merely slower


# ------------------------------------------------------------------- baseline
def load_baseline(path: str = BASELINE_PATH) -> dict[str, str]:
    """key -> justification. Every baselined finding carries a reason; the
    workflow is: land the checker with real findings grandfathered, burn the
    baseline down, keep it empty."""
    try:
        with open(path) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    return {e["key"]: e.get("reason", "") for e in data.get("findings", [])}


# --------------------------------------------------------------------- runner
@dataclass
class RunResult:
    findings: list[Finding] = field(default_factory=list)  # non-baselined
    baselined: list[Finding] = field(default_factory=list)
    stale_baseline: list[str] = field(default_factory=list)
    files: int = 0
    cached_files: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.findings


def discover_files(root: str, roots=DEFAULT_ROOTS,
                   missing: Optional[list[str]] = None) -> list[str]:
    out = []
    for r in roots:
        top = os.path.join(root, r)
        if os.path.isfile(top) and top.endswith(".py"):
            out.append(os.path.relpath(top, root))
            continue
        if not os.path.isdir(top):
            # A typo'd root in a CI invocation must FAIL, not report a
            # clean 0-file run with the gate silently disabled.
            if missing is not None:
                missing.append(r)
            continue
        for dirpath, dirs, files in os.walk(top):
            dirs[:] = sorted(d for d in dirs
                             if d != "__pycache__" and not d.startswith("."))
            for name in sorted(files):
                if name.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, name), root))
    return out


def run(roots=DEFAULT_ROOTS, *, root: str = REPO_ROOT,
        use_cache: bool = True, baseline_path: str = BASELINE_PATH,
        passes: Optional[list[Pass]] = None) -> RunResult:
    t0 = time.monotonic()
    passes = passes if passes is not None else all_passes()
    project = Project(root)
    cache = (ResultCache(_default_cache_path(root), _tool_version())
             if use_cache else None)
    res = RunResult()
    facts: dict[str, dict[str, Any]] = {p.id: {} for p in passes}
    suppressions: dict[str, dict] = {}  # relpath -> suppression table
    per_file: list[Finding] = []
    missing_roots: list[str] = []
    for relpath in discover_files(root, roots, missing=missing_roots):
        res.files += 1
        try:
            with open(os.path.join(root, relpath)) as f:
                source = f.read()
        except OSError:
            continue
        project.analyzed.add(relpath)
        # Path rides in the key: byte-identical files must not alias each
        # other's (path-bearing) findings.
        cache_key = f"{relpath}:{hashlib.sha1(source.encode()).hexdigest()}"
        if cache is not None:
            cache.visit(relpath)
        cached = cache.get(cache_key) if cache is not None else None
        if cached is not None:
            res.cached_files += 1
            for d in cached["findings"]:
                per_file.append(Finding.from_json(d))
            for pid, fact in cached["facts"].items():
                if pid in facts and fact is not None:
                    facts[pid][relpath] = fact
            suppressions[relpath] = cached.get("suppress", {})
            continue
        try:
            ctx = FileCtx(relpath, source)
        except SyntaxError as e:
            per_file.append(Finding("rtcheck", relpath, e.lineno or 0,
                                    f"syntax error: {e.msg}"))
            continue
        suppressions[relpath] = ctx.suppression_table()
        file_findings: list[Finding] = []
        file_facts: dict[str, Any] = {}
        for p in passes:
            if not p.wants(relpath):
                continue
            found, fact = p.check_file(ctx)
            file_findings.extend(
                f for f in found if not ctx.suppressed(f.pass_id, f.line))
            file_facts[p.id] = fact
            if fact is not None:
                facts[p.id][relpath] = fact
        per_file.extend(file_findings)
        if cache is not None:
            cache.put(cache_key, file_findings, file_facts,
                      suppressions[relpath])
    if cache is not None:
        cache.save()

    all_findings = list(per_file)
    for r in missing_roots:
        all_findings.append(Finding(
            "rtcheck", r, 0,
            f"analysis root '{r}' does not exist — fix the path or the "
            f"invocation (a missing root must not pass as clean)"))
    for p in passes:
        # Cross-file findings honor inline suppressions too (the site's
        # table survives caching).
        for f in p.finalize(facts[p.id], project):
            tbl = suppressions.get(f.path)
            if tbl is not None and _suppr_match(tbl, f.pass_id, f.line):
                continue
            all_findings.append(f)

    baseline = load_baseline(baseline_path)
    seen_keys = set()
    occurrences: dict[str, int] = {}
    for f in sorted(all_findings, key=lambda f: (f.path, f.line, f.pass_id)):
        f.occurrence = 1
        base = f.key
        f.occurrence = occurrences[base] = occurrences.get(base, 0) + 1
        seen_keys.add(f.key)
        if f.key in baseline:
            res.baselined.append(f)
        else:
            res.findings.append(f)
    res.stale_baseline = sorted(k for k in baseline if k not in seen_keys)
    res.elapsed_s = time.monotonic() - t0
    return res


# ------------------------------------------------------------------------ CLI
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtcheck",
        description="Static invariant checks for the ray_tpu runtime "
                    "(also exposed as `ray-tpu lint`).")
    ap.add_argument("paths", nargs="*", default=[],
                    help=f"roots to analyze (default: {', '.join(DEFAULT_ROOTS)})")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output for tooling")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore the per-file content-hash result cache")
    ap.add_argument("--baseline", default=BASELINE_PATH,
                    help="baseline file of grandfathered finding keys")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for p in all_passes():
            print(f"{p.id}: {(p.__doc__ or '').strip().splitlines()[0]}")
        return 0

    roots = tuple(args.paths) or DEFAULT_ROOTS
    res = run(roots, use_cache=not args.no_cache,
              baseline_path=args.baseline)

    if args.as_json:
        print(json.dumps({
            "ok": res.ok,
            "findings": [f.to_json() for f in res.findings],
            "baselined": [f.to_json() for f in res.baselined],
            "stale_baseline": res.stale_baseline,
            "files": res.files,
            "cached_files": res.cached_files,
            "elapsed_s": round(res.elapsed_s, 3),
        }, indent=2))
        return 0 if res.ok else 1

    for f in res.findings:
        print(f.render())
    for key in res.stale_baseline:
        print(f"warning: stale baseline entry (no longer found): {key}")
    tail = (f"{res.files} files ({res.cached_files} cached), "
            f"{len(res.findings)} finding(s), "
            f"{len(res.baselined)} baselined, {res.elapsed_s:.2f}s")
    if res.ok:
        print(f"rtcheck: clean — {tail}")
        return 0
    print(f"rtcheck: FAILED — {tail}", file=sys.stderr)
    return 1
