"""async-blocking: asyncio hot paths must never block the event loop.

Every RPC frame, lease grant, heartbeat and scheduler pass in this runtime
rides a handful of event loops (`rpc.EventLoopThread`, the controller/agent
loops, serve's proxy loop). One blocking call inside an `async def` stalls
every connection multiplexed onto that loop — the failure shows up as
cluster-wide latency, not a local bug.

Flags, inside `async def` bodies under ray_tpu/_private/ and ray_tpu/serve/
(nested sync closures are exempt — they run wherever they're called, usually
an executor thread):

- `time.sleep(...)` (use `asyncio.sleep`)
- blocking `subprocess` / `os.system` / `os.popen` calls
- blocking `socket` module calls and recv/accept/connect on socket-ish names
- synchronous file IO: builtin `open(...)` and `.read()/.readlines()/
  .write()` on handles opened in the same async body
- sync RPC bridges that would deadlock or stall the loop: `*.io.run(...)` /
  `EventLoopThread.run`, non-awaited `ray_tpu.get/wait`, and
  `concurrent.futures` `.result()`
- `threading.Lock.acquire()` without a timeout (an unbounded sync lock wait
  parks the whole loop; `with lock:` around short critical sections is fine
  and deliberately not flagged)
"""

from __future__ import annotations

import ast

from tools.rtcheck.astutil import (FunctionStackVisitor, call_keywords,
                                   dotted, terminal_name)
from tools.rtcheck.core import FileCtx, Finding, Pass

_TIME_MODULES = {"time", "_time"}
_SUBPROCESS_FNS = {"run", "call", "check_call", "check_output", "Popen"}
_SOCKET_MODULE_FNS = {"create_connection", "socketpair", "getaddrinfo",
                      "gethostbyname", "socket"}
_SOCKETISH_METHODS = {"recv", "recv_into", "accept", "connect", "sendall"}
_FILE_READ_METHODS = {"read", "readline", "readlines", "write"}


class AsyncBlockingPass(Pass):
    """Flag blocking calls inside async def bodies on runtime hot paths."""

    id = "async-blocking"

    def wants(self, relpath: str) -> bool:
        return ("ray_tpu/_private/" in relpath
                or "ray_tpu/serve/" in relpath)

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], None]:
        v = _Visitor(ctx)
        v.visit(ctx.tree)
        return v.findings, None


class _Visitor(FunctionStackVisitor):
    def __init__(self, ctx: FileCtx):
        super().__init__()
        self.ctx = ctx
        self.findings: list[Finding] = []
        self._awaited: set[int] = set()
        #: per-async-function names bound from open() (flow-lite: a handle
        #: opened in this async body makes later .read()/.write() on that
        #: name blocking too)
        self._open_names: list[set[str]] = []

    # -- track which Call nodes are directly awaited ------------------------
    def visit_Await(self, node: ast.Await):
        if isinstance(node.value, ast.Call):
            self._awaited.add(id(node.value))
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self._open_names.append(set())
        super().visit_AsyncFunctionDef(node)
        self._open_names.pop()

    def visit_Assign(self, node: ast.Assign):
        if (self.in_async_body() and self._open_names
                and isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Name)
                and node.value.func.id == "open"):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self._open_names[-1].add(t.id)
        self.generic_visit(node)

    def _flag(self, node: ast.AST, what: str, fix: str):
        self.findings.append(Finding(
            AsyncBlockingPass.id, self.ctx.path, node.lineno,
            f"blocking {what} inside `async def "
            f"{self.func_stack[-1][1]}` — {fix}",
            col=node.col_offset))

    def visit_Call(self, node: ast.Call):
        if not self.in_async_body() or id(node) in self._awaited:
            self.generic_visit(node)
            return
        func = node.func
        chain = dotted(func)
        name = terminal_name(func)

        # time.sleep — the classic loop stall.
        if chain is not None and "." in chain:
            mod, _, attr = chain.rpartition(".")
            if attr == "sleep" and mod.split(".")[-1] in _TIME_MODULES:
                self._flag(node, "time.sleep()", "use `await asyncio.sleep`")
            elif (attr in _SUBPROCESS_FNS
                  and mod.split(".")[-1] in ("subprocess", "_subprocess")):
                self._flag(node, f"subprocess.{attr}()",
                           "use `asyncio.create_subprocess_exec` or "
                           "run_in_executor")
            elif mod.split(".")[-1] == "os" and attr in ("system", "popen"):
                self._flag(node, f"os.{attr}()", "use run_in_executor")
            elif (attr in _SOCKET_MODULE_FNS
                  and mod.split(".")[-1] == "socket"):
                self._flag(node, f"socket.{attr}()",
                           "use asyncio streams or run_in_executor")
            elif (attr in _SOCKETISH_METHODS
                  and "sock" in mod.split(".")[-1].lower()):
                self._flag(node, f"socket .{attr}()",
                           "use asyncio streams or run_in_executor")
            elif attr == "run" and mod.split(".")[-1] in ("io", "_io_thread"):
                # EventLoopThread.run() bridges sync->async by BLOCKING on a
                # concurrent future; called from a coroutine it stalls (or
                # deadlocks) the loop.
                self._flag(node, "EventLoopThread.run()",
                           "await the coroutine directly")
            elif (attr in ("get", "wait")
                  and mod.split(".")[-1] == "ray_tpu"):
                self._flag(node, f"ray_tpu.{attr}()",
                           "synchronous cluster RPC from a coroutine; move "
                           "to a thread or use the async object APIs")
            elif attr == "result" and mod.split(".")[-1] in (
                    "fut", "future", "cf"):
                self._flag(node, "Future.result()",
                           "await `asyncio.wrap_future(fut)` instead")
            elif attr == "acquire" and "lock" in mod.split(".")[-1].lower():
                kws = call_keywords(node)
                if ("timeout" not in kws and "blocking" not in kws
                        and not node.args):
                    self._flag(node, "Lock.acquire() without timeout",
                               "bound it with `timeout=` or restructure; an "
                               "unbounded sync lock wait parks the loop")
        elif name == "open":
            self._flag(node, "open()",
                       "synchronous file IO; use run_in_executor")
        # .read()/.write() on a handle opened in this async body.
        if (isinstance(func, ast.Attribute)
                and func.attr in _FILE_READ_METHODS
                and isinstance(func.value, ast.Name)
                and self._open_names
                and func.value.id in self._open_names[-1]):
            self._flag(node, f"file .{func.attr}()",
                       "synchronous file IO; use run_in_executor")
        self.generic_visit(node)
