"""event-kinds: every emitted event kind is declared in the KINDS registry.

The cluster event plane (README "Cluster events") indexes and queries
events by their `kind` string. A typo'd kind at an emission site —
`emit_event("actor_detah", ...)` — is silently accepted at runtime (the
plane must never throw from a lifecycle path), lands in the ring with a
kind nothing queries, and is therefore unfindable forever. The registry in
`ray_tpu/_private/events.py` (the `KINDS` dict literal) is the single
source of truth; this pass joins every literal-kind emission site in
ray_tpu/ against it.

Checked call shapes: `emit_event("kind", ...)` / `emit_event(kind="kind")`
and the controller/agent method spelling `self._emit_event(...)` /
`events_mod.build_event(...)`. Non-literal kinds (variables) are out of
scope — the registry check is for the static sites, which is all of them
today.
"""

from __future__ import annotations

import ast
from typing import Any

from tools.rtcheck.core import FileCtx, Finding, Pass

_ID = "event-kinds"

EVENTS_PATH = "ray_tpu/_private/events.py"

#: Function/method names whose first argument (or kind=) is an event kind.
_EMIT_NAMES = ("emit_event", "_emit_event", "build_event")


class EventKindsPass(Pass):
    """emit_event kind literals must be declared in events.KINDS."""

    id = _ID

    def wants(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        facts: dict[str, Any] = {}
        if ctx.path == EVENTS_PATH:
            kinds = _declared_kinds(ctx.tree)
            if kinds:
                facts["kinds"] = kinds
        uses = _emit_sites(ctx)
        if uses:
            facts["uses"] = uses
        return [], facts or None

    def finalize(self, facts: dict[str, Any], project) -> list[Finding]:
        findings: list[Finding] = []
        kinds: dict[str, int] = {}
        for fact in facts.values():
            kinds.update(fact.get("kinds", {}))
        if not kinds:
            if EVENTS_PATH in project.analyzed:
                findings.append(Finding(
                    _ID, EVENTS_PATH, 1,
                    "no declared event kinds found — the events.py KINDS "
                    "registry parsing broke or the registry moved"))
                return findings
            # Restricted-root run (e.g. `rtcheck ray_tpu/serve`): read the
            # registry from disk so emission sites still get checked.
            src = project.read_text(EVENTS_PATH)
            if src is None:
                return []  # tree without an events module (pass fixtures)
            try:
                kinds = _declared_kinds(ast.parse(src))
            except SyntaxError:
                return []
            if not kinds:
                return []
        for path, fact in sorted(facts.items()):
            for use in fact.get("uses", ()):
                if use["kind"] not in kinds:
                    findings.append(Finding(
                        _ID, path, use["line"],
                        f"event kind {use['kind']!r} is not declared in the "
                        f"events.py KINDS registry — an undeclared kind is "
                        f"unqueryable forever (add it to KINDS, or fix the "
                        f"typo)"))
        return findings


def _declared_kinds(tree: ast.AST) -> dict[str, int]:
    """kind -> lineno for every string key of the module-scope
    `KINDS = {...}` dict literal (AnnAssign spelling included)."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        else:
            continue
        if (isinstance(target, ast.Name) and target.id == "KINDS"
                and isinstance(value, ast.Dict)):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    out[k.value] = k.lineno
    return out


def _emit_sites(ctx: FileCtx) -> list[dict]:
    """Every literal-kind emission call in the file."""
    out: list[dict] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None)
        if name not in _EMIT_NAMES:
            continue
        kind = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            kind = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant) \
                        and isinstance(kw.value.value, str):
                    kind = kw.value.value
        if kind is None:
            continue  # dynamic kind: out of scope
        if ctx.suppressed(_ID, node.lineno):
            continue
        out.append({"kind": kind, "line": node.lineno})
    return out
