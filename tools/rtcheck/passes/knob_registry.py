"""knob-registry: every RT_* knob lives in the rtconfig registry.

The typed `rtconfig` registry is the single source of truth for runtime
knobs: flags are env-overridable (`RT_<NAME>`), overridable per-cluster via
`init(_system_config=...)`, and the resolved table propagates cluster-wide.
An ad-hoc `os.environ.get("RT_*")` read bypasses all three — the stray
`RT_DECODE_KERNEL` knob was invisible to `_system_config`, undocumented,
and unpropagated.

Checks across ray_tpu/ (rtconfig.py itself is exempt — it IS the registry):

- every RT_* env **read** must name either a registered flag's env var
  (flagged as a bypass: use `CONFIG.<flag>`) or a BOOTSTRAP_ALLOWLIST entry
  (process identity / pre-config reads, each with a reason below)
- RT_* env **writes** may only name registered or allowlisted vars (writing
  an unknown var means some child reads it ad hoc)
- any other RT_* string literal must at least be a *known* name — an
  unknown name in an error message or help text is a typo or an
  unregistered knob
- every registered flag must appear (as `RT_<NAME>`) in the README knob
  table — `ray-tpu lint` fails when a new flag lands undocumented
"""

from __future__ import annotations

import ast
import re
from typing import Any

from tools.rtcheck.astutil import dotted
from tools.rtcheck.core import FileCtx, Finding, Pass

_ID = "knob-registry"
_RT_NAME = re.compile(r"^RT_[A-Z0-9_]+$")

REGISTRY_PATH = "ray_tpu/_private/rtconfig.py"
README_PATH = "README.md"

#: Env vars legitimately read straight from os.environ, each because it must
#: exist BEFORE the config snapshot does (or identifies the process itself).
BOOTSTRAP_ALLOWLIST = {
    # Cluster bootstrap: how a client finds the controller at all.
    "RT_ADDRESS": "cluster address, read before any config exists",
    # Read at rpc.py import time so chaos tests can arm injection before
    # the first connection; also a registered flag for _system_config use.
    "RT_FAULT_INJECTION": "armed at import time, before config snapshot",
    # Process identity, set by the node agent when spawning workers.
    "RT_WORKER_ID": "worker process identity (spawn env)",
    "RT_NODE_ID": "worker process identity (spawn env)",
    "RT_SESSION": "worker process identity (spawn env)",
    "RT_CONTROLLER": "worker process identity (spawn env)",
    "RT_AGENT": "worker process identity (spawn env)",
    "RT_HOST": "bind host for multi-machine clusters (bootstrap)",
    "RT_AGENT_STANDALONE": "process-mode marker set by the agent entrypoint",
    "RT_JOB_SUBMISSION_ID": "job-driver identity (spawn env)",
    # Native extension bootstrap: read at import, before rtconfig loads.
    "RT_NATIVE_BUILD_DIR": "native build dir, read at import time",
    "RT_DISABLE_NATIVE": "native kill-switch, read at import time",
    # Topology probe paired with the TPU runtime's own TPU_CHIPS.
    "RT_NUM_TPUS": "accelerator count probe, read before init",
}


class KnobRegistryPass(Pass):
    """RT_* env literals must resolve to registered rtconfig flags."""

    id = _ID

    def wants(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        facts: dict[str, Any] = {}
        if ctx.path == REGISTRY_PATH:
            flags = _registered_flags(ctx.tree)
            if flags:
                facts["flags"] = flags
            return [], facts or None
        uses = _env_literal_uses(ctx)
        if uses:
            facts["uses"] = uses
        return [], facts or None

    def finalize(self, facts: dict[str, Any], project) -> list[Finding]:
        findings: list[Finding] = []
        flags: dict[str, int] = {}
        for fact in facts.values():
            flags.update(fact.get("flags", {}))
        if not flags:
            if REGISTRY_PATH in project.analyzed:
                findings.append(Finding(
                    _ID, REGISTRY_PATH, 1,
                    "no registered flags found — rtconfig registry parsing "
                    "broke or the registry moved"))
                return findings
            # Restricted-root run (e.g. `rtcheck ray_tpu/serve`): the
            # registry wasn't scanned — read it from disk so the
            # bypass/unregistered checks stay meaningful.
            src = project.read_text(REGISTRY_PATH)
            if src is None:
                return []  # tree without a registry (pass fixtures)
            try:
                flags = _registered_flags(ast.parse(src))
            except SyntaxError:
                return []
            if not flags:
                return []
        env_of = {f"RT_{name.upper()}": name for name in flags}

        for path, fact in sorted(facts.items()):
            for use in fact.get("uses", ()):
                name, line, kind = use["name"], use["line"], use["kind"]
                if name in BOOTSTRAP_ALLOWLIST:
                    continue
                if name in env_of:
                    if kind == "read":
                        findings.append(Finding(
                            _ID, path, line,
                            f"direct env read of {name} bypasses the "
                            f"rtconfig registry (no _system_config "
                            f"override, no cluster propagation) — use "
                            f"`CONFIG.{env_of[name]}`"))
                    continue  # writes/mentions of registered names are fine
                if kind in ("read", "write"):
                    findings.append(Finding(
                        _ID, path, line,
                        f"{name} is not a registered rtconfig flag (and "
                        f"not bootstrap-allowlisted) — add a `_flag(...)` "
                        f"entry and read it via CONFIG"))
                else:
                    findings.append(Finding(
                        _ID, path, line,
                        f"unknown knob name {name} in a string literal — "
                        f"typo, or an unregistered knob being documented"))

        readme = project.read_text(README_PATH) or ""
        for name in sorted(flags):
            env = f"RT_{name.upper()}"
            if env not in readme:
                findings.append(Finding(
                    _ID, REGISTRY_PATH, flags[name],
                    f"registered flag '{name}' ({env}) is missing from the "
                    f"README knob table"))
        return findings


def _registered_flags(tree: ast.AST) -> dict[str, int]:
    """name -> lineno for every `_flag(\"name\", ...)` call in rtconfig."""
    out: dict[str, int] = {}
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "_flag" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            out[node.args[0].value] = node.lineno
    return out


def _env_literal_uses(ctx: FileCtx) -> list[dict]:
    """Every RT_* string literal in the file, classified read/write/mention.

    read:   os.environ.get("RT_X") / os.environ["RT_X"] (Load) /
            os.getenv("RT_X")
    write:  os.environ["RT_X"] = ... / env.setdefault("RT_X", ...) /
            dict-literal keys inside an env-var mapping
    mention: any other literal (docstrings excluded)
    """
    classified: dict[int, str] = {}  # id(Constant node) -> kind

    def _is_environ(node: ast.AST) -> bool:
        d = dotted(node)
        return d is not None and d.split(".")[-1] in ("environ", "env_vars",
                                                      "env")

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and node.args and isinstance(
                    node.args[0], ast.Constant):
                if f.attr in ("get", "pop") and _is_environ(f.value):
                    classified[id(node.args[0])] = "read"
                elif f.attr == "setdefault" and _is_environ(f.value):
                    classified[id(node.args[0])] = "write"
            elif (isinstance(f, ast.Attribute) and f.attr == "getenv"
                  and node.args and isinstance(node.args[0], ast.Constant)):
                classified[id(node.args[0])] = "read"
        elif isinstance(node, ast.Subscript):
            if _is_environ(node.value) and isinstance(node.slice,
                                                      ast.Constant):
                kind = ("write" if isinstance(node.ctx, (ast.Store, ast.Del))
                        else "read")
                classified[id(node.slice)] = kind
        elif isinstance(node, ast.Dict):
            # Dict-literal keys: env mappings built for child processes
            # ({"RT_X": "1"} passed as spawn env / runtime_env env_vars) —
            # some child will READ that var, so it must be a known name.
            for k in node.keys:
                if isinstance(k, ast.Constant):
                    classified.setdefault(id(k), "write")

    # Docstring Constant nodes are documentation, not code.
    doc_ids = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if (body and isinstance(body[0], ast.Expr)
                    and isinstance(body[0].value, ast.Constant)
                    and isinstance(body[0].value.value, str)):
                doc_ids.add(id(body[0].value))

    uses = []
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and _RT_NAME.match(node.value) and id(node) not in doc_ids):
            if ctx.suppressed(_ID, node.lineno):
                continue
            uses.append({"name": node.value, "line": node.lineno,
                         "kind": classified.get(id(node), "mention")})
    return uses
