"""exception-taxonomy: no swallowed overbroad excepts in _private/ hot
paths; RPC handlers raise only taxonomy exceptions.

Two invariants:

1. **Swallowed overbroad handlers** in ray_tpu/_private/: a bare `except:`
   is always flagged (it eats KeyboardInterrupt/SystemExit — on the worker
   exec path that breaks cancel/timeout delivery, which rides SIGINT). An
   `except BaseException:` is flagged when it *swallows*: no re-raise and
   the bound exception (if any) is never used — catching user-code errors
   into an error blob is legitimate and stays clean.

2. **RPC handler raise taxonomy**: controller `_h_*`/`_p_*` handlers (and
   `_on_request` dispatchers) reply across the wire; whatever they raise is
   re-surfaced in another process. Raising module-local exception classes
   couples peers to private modules and breaks unpickling on version skew —
   handlers may only raise classes from `ray_tpu.exceptions`, the rpc
   transport errors, or stdlib builtins (picklable everywhere). The
   taxonomy is read from the AST of ray_tpu/exceptions.py + rpc.py, so
   adding a class there extends the allowed set automatically.
"""

from __future__ import annotations

import ast
import builtins
from typing import Any

from tools.rtcheck.astutil import terminal_name
from tools.rtcheck.core import FileCtx, Finding, Pass

_ID = "exception-taxonomy"
_TAXONOMY_FILES = ("ray_tpu/exceptions.py", "ray_tpu/_private/rpc.py")
_BUILTIN_EXCS = {n for n in dir(builtins)
                 if isinstance(getattr(builtins, n), type)
                 and issubclass(getattr(builtins, n), BaseException)}
# Stdlib exception classes commonly raised via module attribute.
_STDLIB_EXTRA = {"TimeoutError", "CancelledError", "IncompleteReadError",
                 "JSONDecodeError", "Empty", "Full"}


def _is_handler(name: str) -> bool:
    return (name.startswith("_h_") or name.startswith("_p_")
            or name == "_on_request")


class ExceptionTaxonomyPass(Pass):
    """Swallowed bare/overbroad excepts + off-taxonomy handler raises."""

    id = _ID

    def wants(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        findings: list[Finding] = []
        facts: dict[str, Any] = {}
        if ctx.path in _TAXONOMY_FILES:
            facts["taxonomy"] = sorted(_exception_classes(ctx.tree))
        if "ray_tpu/_private/" in ctx.path:
            findings.extend(_check_swallowed(ctx))
        raises = _handler_raises(ctx)
        if raises:
            facts["raises"] = raises
        return findings, facts or None

    def finalize(self, facts: dict[str, Any], project) -> list[Finding]:
        taxonomy = set(_BUILTIN_EXCS) | _STDLIB_EXTRA
        have_tax = False
        for fact in facts.values():
            if fact.get("taxonomy"):
                have_tax = True
            taxonomy.update(fact.get("taxonomy", ()))
        if not have_tax:
            # Restricted-root run: the taxonomy modules weren't scanned —
            # read them from disk rather than false-flagging every
            # legitimate handler raise.
            for relp in _TAXONOMY_FILES:
                src = project.read_text(relp)
                if src is not None:
                    try:
                        taxonomy |= _exception_classes(ast.parse(src))
                    except SyntaxError:
                        pass
        findings = []
        for path, fact in sorted(facts.items()):
            for r in fact.get("raises", ()):
                if r["exc"] not in taxonomy:
                    findings.append(Finding(
                        _ID, path, r["line"],
                        f"RPC handler `{r['fn']}` raises {r['exc']}, which "
                        f"is not in ray_tpu.exceptions / rpc transport "
                        f"errors / stdlib builtins — peers re-surface "
                        f"handler exceptions across the wire, so they must "
                        f"come from the shared taxonomy"))
        return findings


def _exception_classes(tree: ast.AST) -> set[str]:
    """Exception classes defined in (or imported into) a taxonomy module."""
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.add(node.name)
        elif isinstance(node, ast.ImportFrom):
            for alias in node.names:
                out.add(alias.asname or alias.name)
    return out


def _check_swallowed(ctx: FileCtx) -> list[Finding]:
    findings = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        is_bare = node.type is None
        catches_base = (isinstance(node.type, (ast.Name, ast.Attribute))
                        and terminal_name(node.type) == "BaseException")
        if isinstance(node.type, ast.Tuple):
            catches_base = any(terminal_name(e) == "BaseException"
                               for e in node.type.elts)
        if not is_bare and not catches_base:
            continue
        reraises = any(isinstance(n, ast.Raise) and n.exc is None
                       for n in ast.walk(node))
        uses_bound = node.name is not None and any(
            isinstance(n, ast.Name) and n.id == node.name
            for stmt in node.body for n in ast.walk(stmt))
        if is_bare:
            findings.append(Finding(
                _ID, ctx.path, node.lineno,
                "bare `except:` swallows KeyboardInterrupt/SystemExit — "
                "on worker paths that breaks cancel/timeout SIGINT "
                "delivery; catch `Exception` (or name the types)"))
        elif not reraises and not uses_bound:
            findings.append(Finding(
                _ID, ctx.path, node.lineno,
                "`except BaseException:` that neither re-raises nor uses "
                "the exception swallows interpreter-exit signals; catch "
                "`Exception` or handle what you caught"))
    return findings


def _handler_raises(ctx: FileCtx) -> list[dict]:
    """All `raise X(...)` / `raise X` inside RPC handler functions."""
    out = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack: list[str] = []

        def _fn(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        visit_FunctionDef = _fn
        visit_AsyncFunctionDef = _fn

        def visit_Raise(self, node: ast.Raise):
            if not any(_is_handler(f) for f in self.stack):
                return
            exc = node.exc
            if exc is None:
                return  # bare re-raise
            if isinstance(exc, ast.Call):
                exc = exc.func
            name = terminal_name(exc)
            # `raise e` of a caught/local variable: unresolvable statically;
            # lowercase names are assumed to be variables, not classes.
            if name is None or (name[:1].islower() and "Error" not in name):
                return
            if not ctx.suppressed(_ID, node.lineno):
                out.append({"fn": self.stack[-1], "exc": name,
                            "line": node.lineno})

    V().visit(ctx.tree)
    return out
