"""The five invariant passes. Imported lazily by core.all_passes()."""
