"""wire-schema: compact wire tuples must not drift between ends.

The hot-path wire formats (`TaskSpec.__getstate__`, `task_call_tuple` for
`exec_tasks` frames, `actor_call_tuple` for `actor_calls` frames, the
`tasks_done` item) are positional tuples re-built by hand on the consumer
side. Adding a field to one end without the other produced the PR 9 wire
extension bug class; this pass makes the drift a CI failure.

Two mechanisms:

1. **Automatic `__getstate__`/`__setstate__` pairing** — for every class in
   ray_tpu/ defining both: the encoder's tuple arity must equal the
   decoder's unpack arity, every `if len(s) == K:` back-compat branch must
   pad the tuple (a default for the missing field), and the supported
   arities {K...} ∪ {final} must be contiguous — growing the tuple without
   a branch for the previous arity breaks old snapshots/peers and is
   flagged.

2. **`# rtcheck: wire=<name>` markers** — encoders and decoders of one wire
   record carry the same marker; the marker is only the cross-file join
   key, arity is always computed from the AST at the marked site:
   a tuple literal => producer arity; a tuple-unpack assignment (or
   `for a, b, ... in`) => consumer arity; integer subscripts => a minimum
   arity. All producers must agree, every consumer unpack must match, every
   subscript must stay in range, and each wire needs at least one producer
   AND one consumer (deleting half the markers is itself a finding). Marked
   decoder functions get the same back-compat branch check as
   `__setstate__`.

Known wires are listed in REQUIRED_WIRES so wholesale marker deletion
cannot silence the pass.
"""

from __future__ import annotations

import ast
import re
from typing import Any, Optional

from tools.rtcheck.astutil import enclosing_function, statement_at
from tools.rtcheck.core import FileCtx, Finding, Pass

_MARKER_RE = re.compile(r"#\s*rtcheck:\s*wire=([\w.\-]+)")

#: Wire names that MUST have marked producer+consumer sites somewhere under
#: ray_tpu/ — the frame formats the runtime actually ships today. Enforced
#: only when every file holding those markers was scanned this run (a
#: file-scoped invocation must not report phantom marker deletion).
REQUIRED_WIRES = ("exec_tasks.call", "actor_calls.call", "tasks_done.item")
REQUIRED_WIRE_FILES = (
    "ray_tpu/_private/task_spec.py",
    "ray_tpu/_private/lease.py",
    "ray_tpu/_private/worker.py",
    "ray_tpu/_private/worker_proc.py",
)

_ID = "wire-schema"


class WireSchemaPass(Pass):
    """Check wire-tuple encoder/decoder arity agreement and back-compat."""

    id = _ID

    def wants(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    # ------------------------------------------------------------- per file
    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        findings: list[Finding] = []
        facts: dict[str, Any] = {"sites": [], "state_pairs": []}

        for cls, enc, dec in _state_pairs(ctx.tree):
            pair_findings, pair = _check_state_pair(ctx, cls, enc, dec)
            findings.extend(pair_findings)
            if pair is not None:
                facts["state_pairs"].append(pair)

        for lineno, wire in _markers(ctx):
            site, err = _analyze_site(ctx, lineno, wire)
            if err is not None:
                findings.append(Finding(_ID, ctx.path, lineno, err))
            if site is not None:
                facts["sites"].append(site)
                if site["kind"] == "consumer" and site.get("branches"):
                    findings.extend(_check_branch_coverage(
                        ctx, lineno, wire, site))

        if not facts["sites"] and not facts["state_pairs"]:
            facts = None
        return findings, facts

    # ------------------------------------------------------------- finalize
    def finalize(self, facts: dict[str, Any], project) -> list[Finding]:
        findings: list[Finding] = []
        wires: dict[str, list[dict]] = {}
        for path, fact in facts.items():
            for site in fact.get("sites", ()):
                site = dict(site, path=path)
                wires.setdefault(site["wire"], []).append(site)

        # Only meaningful when every marker-holding module was scanned this
        # run — fixture repos have none of them, and a restricted-root run
        # (`rtcheck ray_tpu/serve`, or a single-file invocation) must not
        # report markers it never looked for.
        full_scan = all(p in project.analyzed for p in REQUIRED_WIRE_FILES)
        for wire in REQUIRED_WIRES if full_scan else ():
            if wire not in wires:
                findings.append(Finding(
                    _ID, "ray_tpu/_private/task_spec.py", 1,
                    f"required wire '{wire}' has no `# rtcheck: wire=` "
                    f"marked sites — markers were removed without removing "
                    f"the wire format"))

        for wire, sites in sorted(wires.items()):
            producers = [s for s in sites if s["kind"] == "producer"]
            consumers = [s for s in sites if s["kind"] == "consumer"]
            subscripts = [s for s in sites if s["kind"] == "subscript"]
            if not producers:
                s = sites[0]
                findings.append(Finding(
                    _ID, s["path"], s["line"],
                    f"wire '{wire}' has consumers but no marked producer"))
                continue
            if not consumers and not subscripts:
                s = producers[0]
                findings.append(Finding(
                    _ID, s["path"], s["line"],
                    f"wire '{wire}' has producers but no marked consumer"))
            arities = sorted({p["arity"] for p in producers})
            if len(arities) > 1:
                for p in producers:
                    findings.append(Finding(
                        _ID, p["path"], p["line"],
                        f"wire '{wire}' producers disagree on arity "
                        f"({arities}) — this site builds {p['arity']} "
                        f"fields"))
                continue
            arity = arities[0]
            for c in consumers:
                if c["arity"] != arity:
                    findings.append(Finding(
                        _ID, c["path"], c["line"],
                        f"wire '{wire}' decoder unpacks {c['arity']} fields "
                        f"but the encoder builds {arity} — update the "
                        f"decoder (and add a back-compat branch with a "
                        f"default for old senders)"))
            for s in subscripts:
                if s["min_arity"] > arity:
                    findings.append(Finding(
                        _ID, s["path"], s["line"],
                        f"wire '{wire}' consumer indexes field "
                        f"{s['min_arity'] - 1} but the encoder builds only "
                        f"{arity}"))
        return findings


# ------------------------------------------------------- state pair analysis
def _state_pairs(tree: ast.AST):
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        enc = dec = None
        for item in node.body:
            if isinstance(item, ast.FunctionDef):
                if item.name == "__getstate__":
                    enc = item
                elif item.name == "__setstate__":
                    dec = item
        if enc is not None and dec is not None:
            yield node.name, enc, dec


def _return_tuple_arity(fn: ast.FunctionDef) -> Optional[int]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value, ast.Tuple):
            return len(node.value.elts)
    return None


def _unpack_arity(fn: ast.FunctionDef,
                  var: Optional[str] = None) -> Optional[tuple[int, int]]:
    """(arity, line) of the tuple-unpack assignment in fn — the one whose
    RHS is `var` when given (so an unrelated unpack of some other tuple in
    the same function can't masquerade as the wire decode), else the
    widest."""
    best: Optional[tuple[int, int]] = None
    fallback: Optional[tuple[int, int]] = None
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if not isinstance(t, ast.Tuple):
                    continue
                cand = (len(t.elts), node.lineno)
                if (var is not None and isinstance(node.value, ast.Name)
                        and node.value.id == var):
                    if best is None or cand[0] > best[0]:
                        best = cand
                if fallback is None or cand[0] > fallback[0]:
                    fallback = cand
    return best if best is not None else fallback


def _len_branches(fn: ast.FunctionDef,
                  var: Optional[str] = None) -> list[tuple[int, ast.If]]:
    """[(K, if-node)] for every `if len(<var>) == K:` guard in fn. `var`
    scopes the match to the wire-tuple variable — an unrelated
    `if len(args) == 3:` in the same function must not register as a
    back-compat branch (and then fail the contiguity check)."""
    out = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if (isinstance(t, ast.Compare) and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
                and isinstance(t.left, ast.Call)
                and isinstance(t.left.func, ast.Name)
                and t.left.func.id == "len"
                and len(t.left.args) == 1
                and isinstance(t.left.args[0], ast.Name)
                and (var is None or t.left.args[0].id == var)
                and len(t.comparators) == 1
                and isinstance(t.comparators[0], ast.Constant)
                and isinstance(t.comparators[0].value, int)):
            out.append((t.comparators[0].value, node))
    return out


def _branch_pads(branch: ast.If) -> bool:
    """A back-compat branch must rebuild the tuple (pad with defaults)."""
    for node in ast.walk(branch):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            return True
    return False


def _contiguity(ctx: FileCtx, line: int, label: str, final: int,
                branch_ks: list[int]) -> list[Finding]:
    """Supported arities must form K_min..final with no gap: a gap means the
    tuple grew without a back-compat branch for the previous arity."""
    findings = []
    high = sorted(k for k in set(branch_ks) if k >= final)
    if high:
        # Branching on the CURRENT (or a larger) arity is the typo class
        # where the dev branched on the new size instead of the old one.
        findings.append(Finding(
            _ID, ctx.path, line,
            f"{label}: `len == {high[0]}` back-compat branch is not below "
            f"the decoder's arity {final} — branch on the OLD arity"))
    supported = sorted(set(k for k in branch_ks if k < final) | {final})
    missing = sorted(set(range(supported[0], final + 1)) - set(supported))
    if missing:
        findings.append(Finding(
            _ID, ctx.path, line,
            f"{label}: back-compat gap — handles arities {supported} but "
            f"not {missing}; arity growth must carry a `len(...) == "
            f"{missing[0]}` branch appending a default"))
    return findings


def _check_state_pair(ctx: FileCtx, cls: str, enc: ast.FunctionDef,
                      dec: ast.FunctionDef):
    findings: list[Finding] = []
    enc_arity = _return_tuple_arity(enc)
    # The state tuple is __setstate__'s sole non-self parameter: scope both
    # the unpack and the back-compat branches to IT.
    state_var = (dec.args.args[1].arg if len(dec.args.args) > 1 else None)
    unpack = _unpack_arity(dec, state_var)
    if enc_arity is None or unpack is None:
        return findings, None  # non-tuple state protocol; out of scope
    dec_arity, dec_line = unpack
    label = f"{cls}.__getstate__/__setstate__"
    if enc_arity != dec_arity:
        findings.append(Finding(
            _ID, ctx.path, dec_line,
            f"{label}: encoder builds {enc_arity} fields, decoder unpacks "
            f"{dec_arity}"))
    branch_ks = []
    for k, branch in _len_branches(dec, state_var):
        branch_ks.append(k)
        if not _branch_pads(branch):
            findings.append(Finding(
                _ID, ctx.path, branch.lineno,
                f"{label}: `len == {k}` back-compat branch does not pad "
                f"the tuple with a default"))
    if branch_ks:
        findings.extend(
            _contiguity(ctx, dec_line, label, dec_arity, branch_ks))
    pair = {"class": cls, "enc": enc_arity, "dec": dec_arity,
            "branches": sorted(branch_ks)}
    return findings, pair


# ------------------------------------------------------------- marker sites
def _markers(ctx: FileCtx):
    # Real comments only (ctx.comments is tokenizer-derived): a string
    # literal documenting the marker syntax must not fabricate a wire site.
    for i, ln in ctx.comments.items():
        if "rtcheck:" not in ln:
            continue
        m = _MARKER_RE.search(ln)
        if m:
            yield i, m.group(1)


def _analyze_site(ctx: FileCtx, line: int, wire: str):
    """Classify the statement under a wire marker and compute its arity."""
    stmt = statement_at(ctx.tree, line)
    if stmt is None:
        return None, f"wire '{wire}' marker is not attached to a statement"
    # Producer: a tuple literal (the widest one in the statement) being
    # returned / assigned / passed.
    widest: Optional[ast.Tuple] = None
    for node in ast.walk(stmt):
        if isinstance(node, ast.Tuple) and not isinstance(
                getattr(node, "ctx", None), ast.Store):
            if widest is None or len(node.elts) > len(widest.elts):
                widest = node
    # Consumer: a tuple-unpack assignment or for-target.
    unpack: Optional[int] = None
    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            if isinstance(t, ast.Tuple):
                unpack = len(t.elts)
    elif isinstance(stmt, (ast.For, ast.AsyncFor)) and isinstance(
            stmt.target, ast.Tuple):
        unpack = len(stmt.target.elts)
    if unpack is not None:
        # Scope back-compat branches to the variable actually being
        # decoded at the marked site (the unpack's RHS / the iterated
        # name). Unknown source (subscript, call) => collect NO branches:
        # skipping the contiguity check beats registering some unrelated
        # `len(...)` guard as a wire branch.
        rec_var = None
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Name):
            rec_var = stmt.value.id
        elif (isinstance(stmt, (ast.For, ast.AsyncFor))
              and isinstance(stmt.iter, ast.Name)):
            rec_var = stmt.iter.id
        fn = enclosing_function(ctx.tree, line)
        branches = (sorted(k for k, _ in _len_branches(fn, rec_var))
                    if fn is not None and rec_var is not None else [])
        return {"wire": wire, "line": line, "kind": "consumer",
                "arity": unpack, "branches": branches}, None
    if widest is not None and len(widest.elts) >= 2:
        return {"wire": wire, "line": line, "kind": "producer",
                "arity": len(widest.elts)}, None
    # Subscript consumer: integer indexes into the record.
    max_idx = -1
    for node in ast.walk(stmt):
        if (isinstance(node, ast.Subscript)
                and isinstance(node.slice, ast.Constant)
                and isinstance(node.slice.value, int)
                and node.slice.value >= 0):
            max_idx = max(max_idx, node.slice.value)
    if max_idx >= 0:
        return {"wire": wire, "line": line, "kind": "subscript",
                "min_arity": max_idx + 1}, None
    return None, (f"wire '{wire}' marker site is neither a tuple literal, "
                  f"a tuple unpack, nor an integer subscript")


def _check_branch_coverage(ctx: FileCtx, line: int, wire: str,
                           site: dict) -> list[Finding]:
    return _contiguity(ctx, line, f"wire '{wire}' decoder", site["arity"],
                       site["branches"])
