"""lock-discipline: acquisition order is acyclic; helper-thread classes
don't mutate shared state half-locked.

The runtime's deadlock surface is `threading.Lock`s shared between event
loops, executor threads and helper threads (checkpoint writer, watchdog
monitor, rpc flusher, metrics flusher, log flusher). Two invariant classes:

1. **Acquisition order**: build a lock-order graph from lexical
   `with <lock>:` nesting — plus one level of `self.method()` indirection
   inside a held block (method A holds lock X and calls method B which
   takes lock Y => edge X->Y). A cycle means two threads can deadlock by
   acquiring in opposite orders. Locks are identified per class
   (`ClassName._lock`) or per module for module-level locks; edges are
   merged across files before cycle detection.

2. **Half-locked attributes**: in classes that OWN a helper thread (they
   construct `threading.Thread`/`Timer` somewhere), an attribute assigned
   both inside a `with <lock>:` block and outside any lock (outside
   `__init__`, which runs before the thread exists) is a data-race
   candidate — the lock is decoration on one side. The same check runs at
   module scope (`global`-declared writes vs module-level locks) for the
   metrics-flusher / checkpoint-writer shape, which guards module globals
   rather than instance attributes.

Suppress individual sites with `# rtcheck: disable=lock-discipline` plus a
comment saying why the unlocked write is safe (e.g. single-writer field,
thread not yet started, monotonic flag).
"""

from __future__ import annotations

import ast
from typing import Any, Optional

from tools.rtcheck.astutil import dotted
from tools.rtcheck.core import FileCtx, Finding, Pass

_ID = "lock-discipline"
_LOCK_CTORS = {"Lock", "RLock", "Condition"}
_THREAD_CTORS = {"Thread", "Timer"}


class LockDisciplinePass(Pass):
    """Lock-order cycles + half-locked attribute mutation."""

    id = _ID

    def wants(self, relpath: str) -> bool:
        return relpath.startswith("ray_tpu/")

    def check_file(self, ctx: FileCtx) -> tuple[list[Finding], Any]:
        findings: list[Finding] = []
        classes = []
        for node in ctx.tree.body:
            if isinstance(node, ast.ClassDef):
                cls = _ClassAnalysis(ctx, node)
                findings.extend(cls.check_half_locked())
                if cls.edges or cls.lock_attrs:
                    classes.append(cls.facts())
        # Module scope: the checkpoint writer and metrics flusher guard
        # module globals with module-level locks — same invariants, no class.
        mod = _ModuleAnalysis(ctx)
        findings.extend(mod.check_half_locked())
        if mod.edges or mod.locks:
            classes.append(mod.facts())
        facts = {"classes": classes} if classes else None
        return findings, facts

    def finalize(self, facts: dict[str, Any], project) -> list[Finding]:
        # Merge edges across files (a class reopened/subclassed elsewhere
        # contributes to the same node set) and detect cycles.
        findings: list[Finding] = []
        graph: dict[str, set[str]] = {}
        where: dict[tuple[str, str], tuple[str, int]] = {}
        for path, fact in sorted(facts.items()):
            for cls in fact.get("classes", ()):
                for a, b, line in cls["edges"]:
                    graph.setdefault(a, set()).add(b)
                    where.setdefault((a, b), (path, line))
        for cycle in _find_cycles(graph):
            a, b = cycle[0], cycle[1 % len(cycle)]
            path, line = where.get((a, b), ("ray_tpu", 1))
            pretty = " -> ".join(cycle + [cycle[0]])
            findings.append(Finding(
                _ID, path, line,
                f"lock acquisition cycle: {pretty} — two threads taking "
                f"these in opposite orders deadlock"))
        return findings


def _find_cycles(graph: dict[str, set[str]]) -> list[list[str]]:
    """Distinct elementary cycles (one representative per SCC is enough to
    fail CI; the message names the members)."""
    cycles: list[list[str]] = []
    seen_cycles: set[frozenset] = set()
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: list[str] = []

    def dfs(n: str):
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GRAY:
                cyc = stack[stack.index(m):]
                key = frozenset(cyc)
                if key not in seen_cycles:
                    seen_cycles.add(key)
                    cycles.append(list(cyc))
            elif color.get(m, WHITE) == WHITE:
                if m in color:
                    dfs(m)
        stack.pop()
        color[n] = BLACK

    for n in sorted(graph):
        if color[n] == WHITE:
            dfs(n)
    return cycles


class _ClassAnalysis:
    def __init__(self, ctx: FileCtx, node: ast.ClassDef):
        self.ctx = ctx
        self.node = node
        self.name = node.name
        self.lock_attrs: set[str] = set()
        self.owns_thread = False
        #: method -> locks taken at its top level (not already held)
        self.method_locks: dict[str, set[str]] = {}
        #: (outer_lock, inner_lock, line) lexical nesting edges
        self.edges: list[tuple[str, str, int]] = []
        #: deferred (held_locks, callee, line) for one-level indirection
        self._held_calls: list[tuple[tuple[str, ...], str, int]] = []
        #: attr -> [(locked?, line, method)]
        self.attr_writes: dict[str, list[tuple[bool, int, str]]] = {}
        self._scan()

    # ----------------------------------------------------------- collection
    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """Qualified lock id for a with-item context expr, or None."""
        d = dotted(expr)
        if d is None:
            return None
        if d.startswith("self."):
            attr = d[5:]
            if attr in self.lock_attrs or "lock" in attr.lower():
                return f"{self.name}.{attr}"
            return None
        if "lock" in d.split(".")[-1].lower():
            return f"{self.ctx.path}::{d}"  # module-level / foreign lock
        return None

    def _scan(self):
        # Lock attrs can be created lazily outside __init__ (e.g. a log
        # flusher initializing its lock on first use): collect from every
        # method before classifying writes.
        for item in self.node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._collect_locks(item)
        for item in self.node.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            self._scan_method(item)
        # One level of call indirection: held lock + self.method() whose
        # body takes more locks.
        for held, callee, line in self._held_calls:
            for inner in self.method_locks.get(callee, ()):
                if inner not in held:
                    self.edges.append((held[-1], inner, line))

    def _collect_locks(self, fn: ast.FunctionDef):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                ctor = node.value.func
                nm = ctor.attr if isinstance(ctor, ast.Attribute) else (
                    ctor.id if isinstance(ctor, ast.Name) else None)
                if nm in _LOCK_CTORS:
                    for t in node.targets:
                        d = dotted(t)
                        if d and d.startswith("self."):
                            self.lock_attrs.add(d[5:])

    def _scan_method(self, fn: ast.FunctionDef):
        method = fn.name
        top_locks: set[str] = self.method_locks.setdefault(method, set())

        def walk(node: ast.AST, held: tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue  # nested defs run elsewhere
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    new_held = held
                    for w in child.items:
                        lock = self._lock_name(w.context_expr)
                        if lock is not None:
                            if not held:
                                top_locks.add(lock)
                            if new_held and lock != new_held[-1]:
                                self.edges.append(
                                    (new_held[-1], lock, child.lineno))
                            if lock not in new_held:
                                new_held = new_held + (lock,)
                    walk(child, new_held)
                    continue
                if isinstance(child, ast.Call) and held:
                    d = dotted(child.func)
                    if d and d.startswith("self.") and "." not in d[5:]:
                        self._held_calls.append((held, d[5:], child.lineno))
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        d = dotted(t)
                        if (d and d.startswith("self.")
                                and "." not in d[5:]):
                            attr = d[5:]
                            if (attr not in self.lock_attrs
                                    and not self.ctx.suppressed(
                                        _ID, child.lineno)):
                                self.attr_writes.setdefault(attr, []).append(
                                    (bool(held), child.lineno, method))
                walk(child, held)

        # Thread ownership: any Thread(...) construction in any method.
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                nm = (node.func.attr
                      if isinstance(node.func, ast.Attribute)
                      else node.func.id
                      if isinstance(node.func, ast.Name) else None)
                if nm in _THREAD_CTORS:
                    self.owns_thread = True
        walk(fn, ())

    # --------------------------------------------------------------- checks
    def check_half_locked(self) -> list[Finding]:
        if not self.owns_thread or not self.lock_attrs:
            return []
        findings = []
        for attr, writes in sorted(self.attr_writes.items()):
            locked = [w for w in writes if w[0]]
            unlocked = [w for w in writes if not w[0]
                        and w[2] not in ("__init__",)]
            if locked and unlocked:
                _ok, line, method = unlocked[0]
                lmethods = sorted({w[2] for w in locked})
                findings.append(Finding(
                    _ID, self.ctx.path, line,
                    f"{self.name}.{attr} is written under a lock in "
                    f"{lmethods} but without one in `{method}` — this "
                    f"class owns a helper thread, so the unlocked write "
                    f"races (lock it, or suppress with a why-safe "
                    f"comment)"))
        return findings

    def facts(self) -> dict:
        return {"class": self.name,
                "edges": [list(e) for e in self.edges],
                "locks": sorted(self.lock_attrs)}


class _ModuleAnalysis:
    """Module-scope edition: module-level threading locks guarding module
    globals mutated from helper threads (the metrics flusher / checkpoint
    writer shape). A global written both under a module lock and outside
    one — in a module that starts threads — is the same race as the class
    case; `global`-declared assignment targets are the write set."""

    def __init__(self, ctx: FileCtx):
        self.ctx = ctx
        self.locks: set[str] = set()
        self.owns_thread = False
        self.edges: list[tuple[str, str, int]] = []
        #: global name -> [(locked?, line, fn)]
        self.writes: dict[str, list[tuple[bool, int, str]]] = {}
        self._scan()

    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name) and (expr.id in self.locks
                                           or "lock" in expr.id.lower()):
            return f"{self.ctx.path}::{expr.id}"
        return None

    def _scan(self):
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Call):
                nm = (node.func.attr if isinstance(node.func, ast.Attribute)
                      else node.func.id
                      if isinstance(node.func, ast.Name) else None)
                if nm in _THREAD_CTORS:
                    self.owns_thread = True
        for node in self.ctx.tree.body:
            if isinstance(node, ast.Assign) and isinstance(node.value,
                                                           ast.Call):
                ctor = node.value.func
                nm = ctor.attr if isinstance(ctor, ast.Attribute) else (
                    ctor.id if isinstance(ctor, ast.Name) else None)
                if nm in _LOCK_CTORS:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.locks.add(t.id)
        for node in self.ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_fn(node)

    def _scan_fn(self, fn):
        globals_here: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Global):
                globals_here.update(node.names)
        if not globals_here and not self.locks:
            return

        def walk(node: ast.AST, held: tuple[str, ...]):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda, ast.ClassDef)):
                    continue
                if isinstance(child, (ast.With, ast.AsyncWith)):
                    new_held = held
                    for w in child.items:
                        lock = self._lock_id(w.context_expr)
                        if lock is not None:
                            if new_held and lock != new_held[-1]:
                                self.edges.append(
                                    (new_held[-1], lock, child.lineno))
                            if lock not in new_held:
                                new_held = new_held + (lock,)
                    walk(child, new_held)
                    continue
                if isinstance(child, (ast.Assign, ast.AugAssign)):
                    targets = (child.targets
                               if isinstance(child, ast.Assign)
                               else [child.target])
                    for t in targets:
                        for el in (t.elts if isinstance(t, ast.Tuple)
                                   else [t]):
                            if (isinstance(el, ast.Name)
                                    and el.id in globals_here
                                    and el.id not in self.locks
                                    and not self.ctx.suppressed(
                                        _ID, child.lineno)):
                                self.writes.setdefault(el.id, []).append(
                                    (bool(held), child.lineno, fn.name))
                walk(child, held)

        walk(fn, ())

    def check_half_locked(self) -> list[Finding]:
        if not self.owns_thread or not self.locks:
            return []
        findings = []
        for name, writes in sorted(self.writes.items()):
            locked = [w for w in writes if w[0]]
            unlocked = [w for w in writes if not w[0]]
            if locked and unlocked:
                _ok, line, fn = unlocked[0]
                lfns = sorted({w[2] for w in locked})
                findings.append(Finding(
                    _ID, self.ctx.path, line,
                    f"module global `{name}` is written under a lock in "
                    f"{lfns} but without one in `{fn}` — this module "
                    f"starts a helper thread, so the unlocked write races "
                    f"(lock it, or suppress with a why-safe comment)"))
        return findings

    def facts(self) -> dict:
        return {"class": f"{self.ctx.path}::<module>",
                "edges": [list(e) for e in self.edges],
                "locks": sorted(self.locks)}
