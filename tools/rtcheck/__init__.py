"""rtcheck — invariant-encoding static analysis for the ray_tpu runtime.

Run as `python -m tools.rtcheck` or `ray-tpu lint`. See core.py for the
framework and passes/ for the five invariant passes.
"""

from tools.rtcheck.core import (DEFAULT_ROOTS, Finding, Pass, RunResult,
                                all_passes, load_baseline, main, run)

__all__ = ["DEFAULT_ROOTS", "Finding", "Pass", "RunResult", "all_passes",
           "load_baseline", "main", "run"]
