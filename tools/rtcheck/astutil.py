"""Small AST conveniences shared by the rtcheck passes."""

from __future__ import annotations

import ast
from typing import Optional


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, None for anything dynamic."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def terminal_name(node: ast.AST) -> Optional[str]:
    """Last path component of a Name/Attribute chain ('c' for a.b.c)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def call_keywords(call: ast.Call) -> set[str]:
    return {kw.arg for kw in call.keywords if kw.arg is not None}


class FunctionStackVisitor(ast.NodeVisitor):
    """Tracks the enclosing function/class stack while walking. Subclasses
    read `self.func_stack` ([(is_async, name), ...] innermost last) and
    `self.class_stack`."""

    def __init__(self):
        self.func_stack: list[tuple[bool, str]] = []
        self.class_stack: list[str] = []

    def visit_FunctionDef(self, node: ast.FunctionDef):
        self.func_stack.append((False, node.name))
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef):
        self.func_stack.append((True, node.name))
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_Lambda(self, node: ast.Lambda):
        self.func_stack.append((False, "<lambda>"))
        self.generic_visit(node)
        self.func_stack.pop()

    def visit_ClassDef(self, node: ast.ClassDef):
        self.class_stack.append(node.name)
        self.generic_visit(node)
        self.class_stack.pop()

    def in_async_body(self) -> bool:
        """True when the innermost enclosing function is an `async def`
        (code inside a nested sync closure runs wherever the closure is
        called — usually an executor thread — so it doesn't count)."""
        return bool(self.func_stack) and self.func_stack[-1][0]


def statement_at(tree: ast.AST, line: int) -> Optional[ast.stmt]:
    """Smallest statement whose source span covers `line`."""
    best: Optional[ast.stmt] = None
    for node in ast.walk(tree):
        if not isinstance(node, ast.stmt):
            continue
        end = getattr(node, "end_lineno", node.lineno)
        if node.lineno <= line <= end:
            if best is None or (node.lineno, -end) > (best.lineno,
                                                      -getattr(best, "end_lineno", best.lineno)):
                best = node
    return best


def enclosing_function(tree: ast.AST, line: int):
    """Innermost (Async)FunctionDef whose span covers `line`."""
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                if best is None or node.lineno > best.lineno:
                    best = node
    return best
