"""Early pytest plugin (loaded via -p in pytest.ini, BEFORE fd capture).

The axon TPU tunnel pins jax's backend at interpreter start (its
sitecustomize registers a PJRT plugin when PALLAS_AXON_POOL_IPS is set), so
tests that need the virtual 8-device CPU mesh can't switch platforms
in-process. Re-exec the test run once with a clean environment. This must
happen before pytest's capture plugin redirects fd 1/2, or the re-exec'd
process writes its report into the (discarded) capture tempfiles.
"""

import os
import sys

if os.environ.get("PALLAS_AXON_POOL_IPS") and os.environ.get("RT_TEST_REEXEC") != "1":
    _env = dict(os.environ)
    _env.update(
        RT_TEST_REEXEC="1",
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=8 "
        + os.environ.get("XLA_FLAGS", ""),
    )
    os.execvpe(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], _env)
