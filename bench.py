#!/usr/bin/env python
"""Microbenchmark suite.

Parity target: reference release/microbenchmark/run_microbenchmark.py ->
python/ray/_private/ray_perf.py. Baselines from
release/perf_metrics/microbenchmark.json (BASELINE.md), measured on a
64-vcpu m4.16xlarge; this runs wherever the driver puts it (often 1 vcpu),
so vs_baseline carries the hardware gap as well.

`--smoke` runs only the tasks/actors/objects microbenches with short timing
windows (sub-30s, no TPU / LLM / RLlib sections) — the CI perf gate
(tests/test_perf_smoke.py, `perf` marker, outside the tier-1 budget).

Prints ONE JSON line on stdout:
  {"metric": "microbench_geomean", "value": <geomean of per-metric ratios
   vs baseline>, "unit": "x_baseline", "vs_baseline": ..., "details": {...}}
Detail rows go to stderr.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

BASELINES = {
    "single_client_tasks_sync": 963.0,
    "single_client_tasks_async": 7293.0,
    "multi_client_tasks_async": 22747.0,
    "1_1_actor_calls_sync": 2043.0,
    "1_1_actor_calls_async": 8120.0,
    "n_n_actor_calls_async": 27273.0,
    "single_client_get_calls": 10428.0,
    "single_client_put_calls": 4968.0,
    "single_client_put_gigabytes": 19.4,
}

# Peak bf16 FLOP/s by device kind (public spec sheets); used for the MFU
# line. Unknown kinds fall back to the raw TFLOP/s number with no % claim.
TPU_PEAK_BF16 = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12, "TPU v5e": 197e12,
    "TPU v5p": 459e12, "TPU v5": 459e12,
    "TPU v6 lite": 918e12, "TPU v6e": 918e12,
}

MIN_TIME = 2.0  # per-bench timing window; --smoke shrinks it


def tpu_peak_flops(dev) -> tuple[float | None, str]:
    kind = getattr(dev, "device_kind", "") or ""
    for k, v in TPU_PEAK_BF16.items():
        if kind.lower().startswith(k.lower()):
            return v, kind
    return None, kind or "unknown TPU"


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def timeit(name, fn, multiplier=1, min_time=None):
    """reference ray_perf.py timeit: run fn repeatedly, report ops/s."""
    if min_time is None:
        min_time = MIN_TIME
    fn()  # warmup
    start = time.perf_counter()
    count = 0
    while time.perf_counter() - start < min_time:
        fn()
        count += 1
    elapsed = time.perf_counter() - start
    rate = count * multiplier / elapsed
    log(f"  {name}: {rate:,.1f} /s")
    return rate


def _baseline_ratios(results: dict, baselines: dict) -> dict:
    """Per-metric ratios vs baseline for the geomean. Lanes that cannot
    produce a trustworthy number report a {"fallback": true, ...} detail
    INSTEAD of a result, so under the contract nothing non-positive should
    ever reach here — but a lane bug (e.g. a negative TFLOP/s from a
    non-monotonic timing window) must degrade to "metric excluded", never
    to a near-zero log-ratio dragging vs_baseline to the floor."""
    ratios = {}
    for k, base in baselines.items():
        v = results.get(k)
        if v is None:
            continue
        if not (v > 0.0) or not (base > 0.0):
            log(f"  geomean: excluding {k}={v!r} (non-positive values are "
                f"fallback conditions, not throughput)")
            continue
        ratios[k] = v / base
    return ratios


def _ratio_geomean(ratios: dict) -> float:
    """Geomean of the (already positive) ratio set; 1.0 when empty."""
    if not ratios:
        return 1.0
    return float(np.exp(np.mean([np.log(r) for r in ratios.values()])))


def _transport_info() -> str:
    """Which same-host transport the cluster actually selected: workers
    reach the controller via a unix socket when the private socket dir is
    usable, else loopback TCP (on which asyncio sets TCP_NODELAY and
    rpc.connect re-asserts it). In local mode the driver itself rides the
    in-process LocalConnection either way."""
    try:
        import ray_tpu
        from ray_tpu._private import rpc as _rpc

        port = ray_tpu._head.controller_addr[1]
        path = _rpc._uds_path(port)
        if path is not None and os.path.exists(path):
            return "uds"
        return "tcp+nodelay"
    except Exception:
        return "unknown"


def main(smoke: bool = False):
    global MIN_TIME
    if smoke:
        MIN_TIME = min(MIN_TIME, 0.5)
    import ray_tpu

    ray_tpu.init(num_cpus=4)
    results: dict[str, float] = {}
    extra_details: dict = {}

    transport = _transport_info()
    extra_details["transport"] = transport
    log(f"transport: same-host object/control plane via {transport}")

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Actor:
        def noop(self):
            return None

    # Warm the pool so process startup isn't measured.
    ray_tpu.get([noop.remote() for _ in range(8)], timeout=120)

    log("tasks:")
    results["single_client_tasks_sync"] = timeit(
        "single client tasks sync", lambda: ray_tpu.get(noop.remote(), timeout=60))
    results["single_client_tasks_async"] = timeit(
        "single client tasks async",
        lambda: ray_tpu.get([noop.remote() for _ in range(100)], timeout=120),
        multiplier=100)

    # Multiple drivers submitting concurrently (reference ray_perf.py
    # multi_client_tasks_async: 4 clients x async batches). Clients are
    # worker-resident actors, each submitting its own task batches.
    @ray_tpu.remote(num_cpus=0)
    class TaskClient:
        def run(self, n):
            ray_tpu.get([noop.remote() for _ in range(n)], timeout=120)
            return n

    clients = [TaskClient.remote() for _ in range(4)]
    ray_tpu.get([c.run.remote(10) for c in clients], timeout=120)
    results["multi_client_tasks_async"] = timeit(
        "multi client tasks async",
        lambda: ray_tpu.get([c.run.remote(100) for c in clients],
                            timeout=120),
        multiplier=400)

    log("actor calls:")
    a = Actor.remote()
    ray_tpu.get(a.noop.remote(), timeout=60)
    results["1_1_actor_calls_sync"] = timeit(
        "1:1 actor calls sync", lambda: ray_tpu.get(a.noop.remote(), timeout=60))
    results["1_1_actor_calls_async"] = timeit(
        "1:1 actor calls async",
        lambda: ray_tpu.get([a.noop.remote() for _ in range(100)], timeout=120),
        multiplier=100)
    actors = [Actor.options(num_cpus=0).remote() for _ in range(4)]
    ray_tpu.get([b.noop.remote() for b in actors], timeout=60)
    results["n_n_actor_calls_async"] = timeit(
        "n:n actor calls async",
        lambda: ray_tpu.get(
            [b.noop.remote() for b in actors for _ in range(25)], timeout=120),
        multiplier=100)

    log("objects:")
    small = b"x" * 1024
    ref_small = ray_tpu.put(np.frombuffer(small, dtype=np.uint8))
    results["single_client_get_calls"] = timeit(
        "single client get calls",
        lambda: [ray_tpu.get(ref_small, timeout=60) for _ in range(100)],
        multiplier=100)
    arr_small = np.frombuffer(small, dtype=np.uint8)
    results["single_client_put_calls"] = timeit(
        "single client put calls",
        lambda: [ray_tpu.put(arr_small) for _ in range(100)],
        multiplier=100)

    big = np.random.randint(0, 256, size=100 * 1024 * 1024, dtype=np.uint8)
    gb = big.nbytes / 1e9

    # Hardware context: put bandwidth is one mandatory memcpy into shm, so
    # the host's raw memcpy rate is the ceiling (the 19.4 GB/s baseline was
    # measured on an m4.16xlarge with ~3-4x this box's memory bandwidth).
    scratch = np.empty_like(big)
    np.copyto(scratch, big)
    t0 = time.perf_counter()
    np.copyto(scratch, big)
    hw_memcpy = gb / (time.perf_counter() - t0)
    # The put path copies with the native THREADED memcpy; yardstick it
    # with the same machinery (a single-threaded np.copyto understates the
    # bound on multi-core hosts and swings with ambient load).
    threaded = False
    try:
        from ray_tpu import _native

        if _native.get_lib() is not None:
            mv = memoryview(scratch)
            _native.parallel_memcpy(mv, big)
            t0 = time.perf_counter()
            _native.parallel_memcpy(mv, big)
            hw_memcpy = max(hw_memcpy, gb / (time.perf_counter() - t0))
            threaded = True
    except Exception:
        pass
    mv = None  # a live view would pin the 100MB scratch past the del
    del scratch
    log(f"  host memcpy ceiling: {hw_memcpy:.1f} GB/s"
        f"{' (threaded)' if threaded else ''}")

    def put_big():
        ref = ray_tpu.put(big)
        del ref  # decref frees the segment back to the warm pool

    results["single_client_put_gigabytes"] = timeit(
        "single client put gigabytes", put_big, multiplier=gb)

    if not smoke:
        _bench_channel(results)
        _bench_tpu_matmul(results, extra_details)
        _bench_flash_attention(results, extra_details)
        _bench_llm_decode(results)
        _bench_rllib_ppo(results)

    ray_tpu.shutdown()

    if smoke:
        # Direct-dispatch A/B (perf-gate input, tests/test_perf_smoke.py):
        # the SAME multi-client workload with RT_DIRECT_DISPATCH=0 routes
        # every task through the controller — direct dispatch must beat it.
        _bench_ctrl_path_multi_client(extra_details)
        # Device object plane A/B (perf-gate input): actor→actor 64MB
        # jax.Array handoff, device plane vs RT_DEVICE_OBJECTS=0 host store.
        _bench_device_object_p2p(extra_details)
        # Checkpoint engine: raw save throughput + async-overlap A/B
        # (train-loop step time with async checkpointing vs none vs sync).
        _bench_checkpoint(extra_details)
        # Tracing plane A/B (perf-gate input): single-client async task
        # batches with RT_TRACING unset vs sampled-on — the off path must
        # be free, the sampled-on path must stay under 5% overhead.
        _bench_tracing_overhead(extra_details)
        # Telemetry plane A/B (perf-gate input): sampling off vs
        # RT_TELEMETRY_INTERVAL_S=1 — off is byte-identical (no sampler
        # thread), on must stay under 5% on the task-throughput lane.
        _bench_telemetry_overhead(extra_details)
        # Event plane A/B (perf-gate input): lifecycle-event emission is
        # always-on by default — the driver task hot path must sit within
        # the noise bound of RT_EVENTS_BUFFER=0 (events are emitted at
        # lifecycle rate, never per task).
        _bench_events_overhead(extra_details)
        # Compiled dataflow plane (perf-gate input, ISSUE 15): steady-state
        # us/step for a 3-stage chain through pre-wired shm channels vs the
        # SAME chain as direct-dispatch .remote() calls — the compiled path
        # must be >= 3x faster (the owner/controller are out of the loop).
        _bench_dag_steady_state(extra_details)
        # Serving hot loop (perf-gate input, ISSUE 13): end-to-end SSE
        # streaming decode through proxy+replica+token-ring vs the SAME
        # engine isolated in-process — the ratio is the serving tax. The
        # BENCH_r05 per-token reply path measured ~0.045x; the token-ring
        # path must hold >= 0.5x under 4 concurrent streaming clients.
        _bench_serve_decode_e2e(extra_details)
        # Pipeline-parallel decode (perf-gate input, ISSUE 18): 2-stage
        # PipelinedEngine vs the single-process ContinuousEngine at matched
        # total parameters. The gate is core-aware: >= 1.3x where the box
        # has cores for both stages to run concurrently; on constrained
        # boxes (both stage processes time-slicing one core) the pipeline
        # cannot express its parallelism and the gate is a sanity floor.
        # Zero-RPC steady state is asserted from the stages' resolve
        # counters regardless of cores.
        _bench_llm_pipeline_decode(extra_details)
        # Overload & admission control (perf-gate input, ISSUE 17):
        # admission-off A/B on the handle path (the plane must be free
        # when budgets aren't binding) + a ~10x SSE overload storm against
        # a capped LLM deployment — every client resolves, queue-full
        # sheds return in milliseconds, admitted streams make goodput.
        _bench_serve_overload(extra_details)
        # Cross-host streaming & multi-proxy fan-out (perf-gate input,
        # ISSUE 20): force-push legs prove the push-stream transport beats
        # the per-item fallback a remote replica otherwise degrades to,
        # and a 2-proxy fleet holds aggregate goodput against one proxy.
        # TTFT p50/p99 under the 16-client heavy-tailed storm ride along.
        _bench_serve_fanout(extra_details)
        # Streaming shuffle (perf-gate input, ISSUE 19): the SAME
        # multi-block random_shuffle with RT_DATA_PIPELINED_EXCHANGE=1 vs
        # =0 (reduce-side work held until the full map wave lands), in
        # GB/s, plus a single-process numpy take()-style shuffle of the
        # same rows as the local floor. The speedup gate is core-aware:
        # >= 1.5x where map and consolidation tasks can actually overlap;
        # on a 1-core box the pipelined mode's extra consolidation hops
        # are pure overhead and the gate is a noise-widened sanity floor.
        _bench_data_shuffle(extra_details)
        # Streaming ingest (perf-gate input, ISSUE 19): Dataset.iter_batches
        # end-to-end — read tasks through the streamed exchange window into
        # driver-side numpy batches without materializing the dataset.
        _bench_data_ingest(extra_details)

    ratios = _baseline_ratios(results, BASELINES)
    # put-GB/s is bounded by this host's memcpy bandwidth (one mandatory
    # copy into shm); the 19.4 GB/s baseline box had ~4x this box's memory
    # bandwidth. Judge the metric against the reachable ceiling and record
    # both numbers (raw ratio kept in details as put_gigabytes_raw_ratio).
    put_raw_ratio = None
    if "single_client_put_gigabytes" in ratios:
        put_raw_ratio = ratios["single_client_put_gigabytes"]
        capped_baseline = min(BASELINES["single_client_put_gigabytes"], hw_memcpy)
        ratios["single_client_put_gigabytes"] = (
            results["single_client_put_gigabytes"] / capped_baseline)
        log(f"  (put GB/s judged vs min(baseline, memcpy ceiling)="
            f"{capped_baseline:.1f} GB/s; raw ratio {put_raw_ratio:.3f})")
    geomean = _ratio_geomean(ratios)
    details = {k: round(v, 1) for k, v in results.items()}
    details["hw_memcpy_gbps"] = round(hw_memcpy, 1)
    details["ratios"] = {k: round(r, 3) for k, r in ratios.items()}
    if put_raw_ratio is not None:
        details["put_gigabytes_raw_ratio"] = round(put_raw_ratio, 3)
    if smoke:
        details["smoke"] = True
    details.update(extra_details)
    print(json.dumps({
        "metric": "microbench_geomean",
        "value": round(geomean, 4),
        "unit": "x_baseline",
        "vs_baseline": round(geomean, 4),
        "details": details,
    }), flush=True)


def _bench_ctrl_path_multi_client(details: dict):
    """Controller-path comparison run for the multi-client workload
    (smoke only): a fresh cluster with RT_DIRECT_DISPATCH=0, so every
    plain task rides the classic controller dispatch. Reported as
    `multi_client_tasks_async_controller_path` (details only — not a
    ratio metric; it exists to prove direct dispatch earns its keep)."""
    import ray_tpu

    prev = os.environ.get("RT_DIRECT_DISPATCH")
    os.environ["RT_DIRECT_DISPATCH"] = "0"
    try:
        ray_tpu.init(num_cpus=4)

        @ray_tpu.remote
        def noop():
            return None

        @ray_tpu.remote(num_cpus=0)
        class TaskClient:
            def run(self, n):
                ray_tpu.get([noop.remote() for _ in range(n)], timeout=120)
                return n

        clients = [TaskClient.remote() for _ in range(4)]
        ray_tpu.get([c.run.remote(10) for c in clients], timeout=120)
        details["multi_client_tasks_async_controller_path"] = round(timeit(
            "multi client tasks async (controller path)",
            lambda: ray_tpu.get([c.run.remote(100) for c in clients],
                                timeout=120),
            multiplier=400), 1)
    except Exception as e:
        log(f"  controller-path comparison skipped: {e}")
    finally:
        if prev is None:
            os.environ.pop("RT_DIRECT_DISPATCH", None)
        else:
            os.environ["RT_DIRECT_DISPATCH"] = prev
        try:
            ray_tpu.shutdown()
        except Exception:
            pass


def _bench_device_object_p2p(details: dict):
    """Actor→actor handoff of a 64MB jax.Array: producer.make() -> ref ->
    consumer.consume(ref), timed end to end, with the device object plane
    ON vs OFF (RT_DEVICE_OBJECTS=0 = today's host-store path). The device
    plane skips the producer-side host materialization the host path pays
    at return time (jax.Array pickling copies device bytes to host before
    the shm write) — the A/B is the perf gate's proof the plane earns its
    keep (tests/test_perf_smoke.py asserts device >= 1.5x host)."""
    import ray_tpu

    mb = 64
    n = (mb << 20) // 4  # float32 elements

    def run_once(plane_on: bool) -> float:
        prev = os.environ.get("RT_DEVICE_OBJECTS")
        # Force BOTH legs (ambient RT_DEVICE_OBJECTS=0 must not silently
        # turn the A into a second B and fail the gate at ~1.0x).
        os.environ["RT_DEVICE_OBJECTS"] = "1" if plane_on else "0"
        try:
            ray_tpu.init(num_cpus=4)

            @ray_tpu.remote(num_cpus=0)
            class Producer:
                def __init__(self):
                    self._arr = None

                def make(self, i):
                    # Hand off an EXISTING device-resident array (the
                    # steady-state train/llm shape: weights/activations
                    # already live on device) — production cost would
                    # dilute the transfer A/B identically on both sides.
                    import jax.numpy as jnp

                    if self._arr is None:
                        self._arr = jnp.full((n,), 7.0, jnp.float32)
                        self._arr.block_until_ready()
                    return self._arr

            @ray_tpu.remote(num_cpus=0)
            class Consumer:
                def consume(self, a):
                    return int(a.nbytes)  # array fully materialized at decode

            p, c = Producer.remote(), Consumer.remote()

            def handoff(i):
                assert ray_tpu.get(c.consume.remote(p.make.remote(i)),
                                   timeout=120) == mb << 20

            handoff(0)  # warm both processes (jax import, pools)
            iters = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < max(MIN_TIME, 1.0):
                iters += 1
                handoff(iters)
            dt = time.perf_counter() - t0
            return iters * (mb << 20) / 1e9 / dt
        finally:
            if prev is None:
                os.environ.pop("RT_DEVICE_OBJECTS", None)
            else:
                os.environ["RT_DEVICE_OBJECTS"] = prev
            try:
                ray_tpu.shutdown()
            except Exception:
                pass

    try:
        dev = run_once(plane_on=True)
        host = run_once(plane_on=False)
    except Exception as e:
        log(f"  device_object_p2p skipped: {e}")
        return
    log(f"  device_object_p2p: device {dev:.2f} GB/s vs host store "
        f"{host:.2f} GB/s ({dev / max(host, 1e-9):.2f}x)")
    details["device_object_p2p_gbps"] = round(dev, 2)
    details["device_object_p2p_host_gbps"] = round(host, 2)


def _ab_overhead_lane(key: str, run_once, details: dict, pairs: int = 3):
    """Interleaved A/B overhead estimator shared by the zero-cost-when-off
    plane lanes (tracing, telemetry). Runs `pairs` (off, on) leg pairs
    with the order alternating each pair (cancels warmup/thermal position
    bias) and gates on the RATIO OF MEDIANS: on 1-core CI boxes single
    legs swing 0.6x-1.4x for the SAME build back to back, so a best-of
    estimator latches onto one outlier window and reads past the 5%
    budget in BOTH directions; the median discards outliers on each side,
    and only a sustained shift — an actual overhead — moves the ratio."""
    import statistics

    budget = 1.05  # the spec'd bound, enforced whenever the box can resolve it
    off_rates: list[float] = []
    on_rates: list[float] = []

    def _noise_bound() -> float:
        # A 5% budget is only meaningful when the measurement can resolve
        # 5%: the gate widens to 3x the legs' relative MAD (~3 standard
        # errors of the ratio-of-medians). On a quiet CI box (rel-MAD
        # 1-2%) this IS the 1.05 gate; on a noisy-neighbor box whose legs
        # swing 2x+ at multi-second dwell, it still catches gross
        # regressions while refusing to flake on ambient drift.
        devs = ([abs(r / max(off, 1e-9) - 1.0) for r in off_rates]
                + [abs(r / max(on, 1e-9) - 1.0) for r in on_rates])
        return max(budget, 1.0 + 3.0 * statistics.median(devs))

    try:
        pair = 0
        while True:
            for _ in range(pairs):
                order = (False, True) if pair % 2 == 0 else (True, False)
                for leg_on in order:
                    (on_rates if leg_on else off_rates).append(
                        run_once(leg_on))
                pair += 1
            off = statistics.median(off_rates)
            on = statistics.median(on_rates)
            bound = _noise_bound()
            if off / max(on, 1e-9) <= bound or pair >= 2 * pairs:
                break
            # Over the bound on the first window: the box drifts by tens
            # of percent at the multi-second scale, so extend the window
            # and pool — a wider median averages the drift out, while a
            # REAL regression reads over the bound in the pooled window
            # too.
            log(f"  {key}_overhead read {off / max(on, 1e-9):.3f}x over "
                f"{pair} pairs — extending the measurement window")
    except Exception as e:
        log(f"  {key}_overhead skipped: {e}")
        return
    log(f"  {key}_overhead: off {off:,.0f}/s vs on {on:,.0f}/s "
        f"({off / max(on, 1e-9):.3f}x, median of {pair} interleaved "
        f"pairs; gate bound {bound:.3f}x)")
    details[f"{key}_overhead_bound"] = round(bound, 3)
    details[f"{key}_off_tasks_s"] = round(off, 1)
    details[f"{key}_on_tasks_s"] = round(on, 1)
    # Best off window: the "compiled-in-but-disarmed is free" sanity gate
    # compares against the main run's (single-window) rate, so it gets
    # the best-of estimator — "did ANY off window reach baseline-class
    # throughput" — while the off-vs-on budget above uses the medians.
    details[f"{key}_off_best_tasks_s"] = round(max(off_rates), 1)
    details[f"{key}_overhead"] = round(off / max(on, 1e-9), 3)


def _bench_tracing_overhead(details: dict):
    """Tracing-plane A/B (smoke only; README "Tracing & timeline"): the
    single_client_tasks_async workload on a fresh cluster with RT_TRACING
    unset vs sampled-on (RT_TRACING=1, RT_TRACE_SAMPLE=0.01 — the
    production head-sampling shape). The perf gate
    (tests/test_perf_smoke.py, RT_RUN_PERF=1) asserts the off path sits
    within noise of the main run's rate (tracing compiled in but disarmed
    costs nothing) and sampled-on costs < 1.05x."""
    import ray_tpu

    def run_once(tracing_on: bool) -> float:
        prev_t = os.environ.pop("RT_TRACING", None)
        prev_s = os.environ.pop("RT_TRACE_SAMPLE", None)
        if tracing_on:
            os.environ["RT_TRACING"] = "1"
            os.environ["RT_TRACE_SAMPLE"] = "0.01"
        try:
            ray_tpu.init(num_cpus=4)

            @ray_tpu.remote
            def noop():
                return None

            ray_tpu.get([noop.remote() for _ in range(8)], timeout=120)
            return timeit(
                f"single client tasks async "
                f"(tracing {'sampled-on' if tracing_on else 'off'})",
                lambda: ray_tpu.get([noop.remote() for _ in range(100)],
                                    timeout=120),
                multiplier=100, min_time=max(MIN_TIME, 1.0))
        finally:
            for k, v in (("RT_TRACING", prev_t), ("RT_TRACE_SAMPLE", prev_s)):
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            try:
                ray_tpu.shutdown()
            except Exception:
                pass

    _ab_overhead_lane("tracing", run_once, details)


def _bench_telemetry_overhead(details: dict):
    """Telemetry-plane A/B (smoke only; README "Telemetry & profiling"):
    the single_client_tasks_async workload with RT_TELEMETRY_INTERVAL_S
    unset vs armed at 1s (the production cadence). The perf gate
    (tests/test_perf_smoke.py, RT_RUN_PERF=1) asserts the off path sits
    within noise of the main run's rate (the plane compiled in but
    disarmed is free — no sampler thread anywhere) and armed sampling
    costs < 1.05x. Interleaved pairs, same estimator as the tracing
    lane, against shared-CI-box noise."""
    import ray_tpu

    def run_once(telemetry_on: bool) -> float:
        prev = os.environ.pop("RT_TELEMETRY_INTERVAL_S", None)
        if telemetry_on:
            os.environ["RT_TELEMETRY_INTERVAL_S"] = "1"
        try:
            ray_tpu.init(num_cpus=4)

            @ray_tpu.remote
            def noop():
                return None

            ray_tpu.get([noop.remote() for _ in range(8)], timeout=120)
            return timeit(
                f"single client tasks async "
                f"(telemetry {'on' if telemetry_on else 'off'})",
                lambda: ray_tpu.get([noop.remote() for _ in range(100)],
                                    timeout=120),
                multiplier=100, min_time=max(MIN_TIME, 1.0))
        finally:
            if prev is None:
                os.environ.pop("RT_TELEMETRY_INTERVAL_S", None)
            else:
                os.environ["RT_TELEMETRY_INTERVAL_S"] = prev
            try:
                ray_tpu.shutdown()
            except Exception:
                pass

    _ab_overhead_lane("telemetry", run_once, details)


def _bench_events_overhead(details: dict):
    """Event-plane A/B (smoke only; README "Cluster events"): the
    single_client_tasks_async workload with the plane at its default
    (always-on, RT_EVENTS_BUFFER=2048) vs disabled (RT_EVENTS_BUFFER=0).
    The perf gate (tests/test_perf_smoke.py, RT_RUN_PERF=1) asserts the
    default-on path stays within the noise bound of plane-off: lifecycle
    events are emitted at transition rate — NOTHING on the per-task hot
    path emits, so the measured overhead is the cost of a handful of
    bounded-ring appends per cluster lifetime."""
    import ray_tpu

    def run_once(events_on: bool) -> float:
        prev = os.environ.pop("RT_EVENTS_BUFFER", None)
        if not events_on:
            os.environ["RT_EVENTS_BUFFER"] = "0"
        try:
            ray_tpu.init(num_cpus=4)

            @ray_tpu.remote
            def noop():
                return None

            ray_tpu.get([noop.remote() for _ in range(8)], timeout=120)
            return timeit(
                f"single client tasks async "
                f"(events {'on' if events_on else 'off'})",
                lambda: ray_tpu.get([noop.remote() for _ in range(100)],
                                    timeout=120),
                multiplier=100, min_time=max(MIN_TIME, 1.0))
        finally:
            if prev is None:
                os.environ.pop("RT_EVENTS_BUFFER", None)
            else:
                os.environ["RT_EVENTS_BUFFER"] = prev
            try:
                ray_tpu.shutdown()
            except Exception:
                pass

    _ab_overhead_lane("events", run_once, details)


def _bench_dag_steady_state(details: dict):
    """Compiled dataflow plane A/B (smoke only; README "Compiled graphs"):
    us/step for a 3-stage chain executed through a compiled graph
    (`execute().get()` per step — pre-negotiated shm channels, zero
    per-call RPC) vs the SAME chain as direct-dispatch `.remote()` calls.
    Both legs share ONE cluster (no env flip needed) and interleave
    through the shared ratio-of-medians estimator; the "overhead" the
    lane reports is direct/compiled — the inverse of the speedup — so
    the estimator's extension condition short-circuits. The perf gate
    (tests/test_perf_smoke.py, RT_RUN_PERF=1) asserts compiled >= 3x."""
    import ray_tpu

    cdag = None
    ok = False
    try:
        ray_tpu.init(num_cpus=4)
        from ray_tpu.dag import InputNode
        from ray_tpu.dag import compile as dag_compile

        @ray_tpu.remote
        def f(x):
            return x + 1

        @ray_tpu.remote
        def g(x):
            return x * 2

        @ray_tpu.remote
        def h(x):
            return x - 3

        with InputNode() as inp:
            dag = h.bind(g.bind(f.bind(inp)))
        cdag = dag_compile(dag)

        def compiled_step():
            assert cdag.execute(4).get(timeout=60) == 7

        def direct_step():
            assert ray_tpu.get(h.remote(g.remote(f.remote(4))),
                               timeout=60) == 7

        compiled_step()  # warm both paths (stage loops up, pool workers)
        direct_step()

        def run_once(compiled_leg: bool) -> float:
            return timeit(
                f"dag 3-stage chain "
                f"({'compiled' if compiled_leg else 'direct dispatch'})",
                compiled_step if compiled_leg else direct_step,
                min_time=max(MIN_TIME, 1.0))

        _ab_overhead_lane("dag_steady_state", run_once, details)
        ok = True
    except Exception as e:
        log(f"  dag_steady_state skipped: {e}")
    finally:
        # teardown runs on the failure paths too (idempotent): a skipped
        # lane must not leave the graph's rtch_* shm segments behind.
        if cdag is not None:
            try:
                cdag.teardown()
            except Exception:
                pass
        try:
            ray_tpu.shutdown()
        except Exception:
            pass
    if not ok:
        return
    on = details.get("dag_steady_state_on_tasks_s")    # compiled steps/s
    off = details.get("dag_steady_state_off_tasks_s")  # direct steps/s
    if on and off:
        details["dag_compiled_us_step"] = round(1e6 / on, 1)
        details["dag_direct_us_step"] = round(1e6 / off, 1)
        details["dag_steady_state_speedup"] = round(on / off, 2)
        log(f"  dag_steady_state: compiled {1e6 / on:.0f} us/step vs "
            f"direct dispatch {1e6 / off:.0f} us/step ({on / off:.1f}x)")


# ---- compiled-graph channel round-trip (native futex ring) ---------------
def _bench_checkpoint(details: dict):
    """Checkpoint engine (README "Checkpointing & storage"), smoke only.

    Reports:
      checkpoint_save_gbps          sync save throughput to local storage
      checkpoint_base_step_s        fake train-loop step, no checkpointing
      checkpoint_async_step_s       ... with save_async every step
      checkpoint_sync_step_s        ... with blocking save every step
      checkpoint_async_step_overhead  async_step / base_step

    The perf gate (tests/test_perf_smoke.py, RT_RUN_PERF=1) asserts async
    overhead < 1.2x and async step time < sync step time — i.e. the
    engine actually hides commit latency from the step path."""
    import shutil
    import tempfile
    import time as _time

    import numpy as np

    from ray_tpu.train import checkpoint as ckpt_mod

    root = tempfile.mkdtemp(prefix="rt_bench_ckpt_")
    try:
        rng = np.random.RandomState(0)
        big_state = {f"w{i}": rng.rand(1024, 1024) for i in range(8)}  # 64MB
        nbytes = sum(a.nbytes for a in big_state.values())
        t0 = _time.perf_counter()
        ckpt_mod.save(big_state, os.path.join(root, "big", "ck"))
        dt = _time.perf_counter() - t0
        details["checkpoint_save_gbps"] = round(nbytes / dt / 1e9, 3)
        log(f"  checkpoint save: {nbytes / dt / 1e9:.2f} GB/s "
            f"({nbytes >> 20}MB in {dt * 1e3:.0f}ms)")

        # Async-overlap A/B: a ~10ms device-bound step (the host blocks on
        # the accelerator — modeled as a sleep, which is also honest on
        # the 1-core CI sandbox where two CPU-bound threads cannot
        # overlap); a checkpoint of a 4MB jax state every 4th step (host
        # views snapshot zero-copy; the writer must digest+write one save
        # inside each 4-step window to keep up). Sync save pays the full
        # write on the step path; async must hide it.
        import jax.numpy as jnp

        state = {"w": jnp.asarray(rng.rand(512, 1024))}  # 4MB
        every = 4

        def step():
            _time.sleep(0.01)

        def loop(mode: str, n: int = 32) -> float:
            d = os.path.join(root, mode)
            handles = []
            t0 = _time.perf_counter()
            for i in range(n):
                step()
                if i % every:
                    continue
                if mode == "async":
                    handles.append(ckpt_mod.save_async(
                        state, os.path.join(d, f"ck{i:04d}"), step=i))
                elif mode == "sync":
                    ckpt_mod.save(state, os.path.join(d, f"ck{i:04d}"),
                                  step=i)
            stepped = _time.perf_counter() - t0
            for h in handles:
                h.result(120)  # drain off the timed region
            return stepped / n

        loop("warm", 4)  # warm numpy/engine paths
        base = loop("base")
        async_s = loop("async")
        sync_s = loop("sync")
        details["checkpoint_base_step_s"] = round(base, 5)
        details["checkpoint_async_step_s"] = round(async_s, 5)
        details["checkpoint_sync_step_s"] = round(sync_s, 5)
        details["checkpoint_async_step_overhead"] = round(async_s / base, 3)
        log(f"  checkpoint overlap: base {base * 1e3:.1f}ms, "
            f"async {async_s * 1e3:.1f}ms "
            f"({async_s / base:.2f}x), sync {sync_s * 1e3:.1f}ms")
    except Exception as e:
        log(f"  checkpoint bench skipped: {e}")
    finally:
        shutil.rmtree(root, ignore_errors=True)


def _bench_channel(results: dict):
    try:
        import multiprocessing as mp
        import time as _time

        from ray_tpu.experimental.channel import Channel

        name = f"bench_{os.getpid()}"
        req, rep = Channel(name + "_q"), Channel(name + "_p")
        nmsg = 2000

        def _echo(nm, k):
            import sys as _s

            _s.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
            from ray_tpu.experimental.channel import Channel as C

            a, b = C(nm + "_q", _create=False), C(nm + "_p", _create=False)
            for _ in range(k):
                b.write(a.read(timeout=60))

        proc = mp.get_context("fork").Process(target=_echo, args=(name, nmsg),
                                              daemon=True)
        proc.start()
        try:
            payload = b"x" * 64
            for _ in range(50):  # warm
                req.write(payload)
                rep.read(timeout=60)
            t0 = _time.perf_counter()
            for _ in range(nmsg - 50):
                req.write(payload)
                rep.read(timeout=60)
            rt_us = (_time.perf_counter() - t0) / (nmsg - 50) * 1e6
            results["channel_rtt_us"] = rt_us
            log(f"  compiled-graph channel: {rt_us:.1f} us/round-trip "
                f"(shm futex ring, cross-process)")
        finally:
            proc.join(timeout=10)
            if proc.is_alive():
                proc.terminate()
            req.close(unlink=True)
            rep.close(unlink=True)
    except Exception as e:
        log(f"  channel bench skipped: {e}")


# ---- TPU matmul MFU (single chip), when a TPU is reachable ---------------
def _bench_tpu_matmul(results: dict, details: dict):
    try:
        import jax
        import jax.numpy as jnp

        if jax.devices()[0].platform != "tpu":
            return
        n = 4096
        x = jax.random.normal(jax.random.PRNGKey(0), (n, n),
                              dtype=jnp.bfloat16) / (n ** 0.5)

        def chain(a, iters):
            # lax.fori_loop keeps the whole chain in ONE device program
            # and only a scalar comes back: the long-vs-short slope
            # isolates pure matmul time even over a slow tunnel.
            y = jax.lax.fori_loop(0, iters, lambda i, y: y @ x, a)
            return jnp.float32(y.sum())

        f = jax.jit(chain, static_argnums=1)

        def run(iters):
            t0 = time.perf_counter()
            float(f(x, iters))  # scalar materialization
            return time.perf_counter() - t0

        run(2)  # compile both variants ahead of timing
        run(130)
        t_short = min(run(2) for _ in range(3))
        t_long = min(run(130) for _ in range(3))
        per_matmul = (t_long - t_short) / 128
        if per_matmul <= 0:
            details["tpu_matmul"] = {
                "fallback": True,
                "reason": "non-monotonic timing (link noise dominated)"}
            log("  tpu matmul: timing unreliable (long chain not slower "
                "than short); no TFLOP/s claimed")
            return
        flops = 2 * n**3 / per_matmul
        results["tpu_matmul_tflops"] = flops / 1e12
        peak, kind = tpu_peak_flops(jax.devices()[0])
        if peak is not None:
            mfu = flops / peak
            details["tpu_matmul_mfu"] = round(mfu, 3)
            log(f"  tpu matmul: {flops/1e12:.1f} TFLOP/s "
                f"({mfu*100:.1f}% of {kind} bf16 peak)")
        else:
            log(f"  tpu matmul: {flops/1e12:.1f} TFLOP/s ({kind})")
    except Exception as e:  # no TPU in this environment
        log(f"  tpu matmul skipped: {e}")


# ---- Pallas flash attention TFLOP/s (single chip) ------------------------
def _bench_flash_attention(results: dict, details: dict):
    """Times the Pallas kernel directly. A shape rejection (ValueError) or
    an unreliable timing window is reported as an explicit
    {"fallback": true, "reason": ...} detail — never as a negative
    TFLOP/s number polluting the results."""
    try:
        import jax
        import jax.numpy as jnp

        if jax.devices()[0].platform != "tpu":
            return
        from ray_tpu.ops.flash_attention import flash_attention

        b_, s_, h_, d_ = 4, 2048, 8, 128
        key = jax.random.PRNGKey(0)
        qa = jax.random.normal(key, (b_, s_, h_, d_), jnp.bfloat16)
        ka = jax.random.normal(key, (b_, s_, h_, d_), jnp.bfloat16)
        va = jax.random.normal(key, (b_, s_, h_, d_), jnp.bfloat16)

        def attn_chain(qx, iters):
            def body(i, acc):
                return flash_attention(acc, ka, va, causal=True)
            y = jax.lax.fori_loop(0, iters, body, qx)
            return jnp.float32(y.astype(jnp.float32).sum())

        fa = jax.jit(attn_chain, static_argnums=1)

        def run_a(iters):
            t0 = time.perf_counter()
            float(fa(qa, iters))
            return time.perf_counter() - t0

        try:
            run_a(2)
        except ValueError as e:
            # Kernel rejected the bench shape: an explicit fallback detail,
            # not a bogus throughput number.
            details["flash_attention"] = {"fallback": True, "reason": str(e)}
            log(f"  flash attention: Pallas kernel rejected bench shape "
                f"(b{b_} s{s_} h{h_} d{d_}): {e}")
            return
        run_a(34)
        t_short = min(run_a(2) for _ in range(3))
        t_long = min(run_a(34) for _ in range(3))
        per_call = (t_long - t_short) / 32
        if per_call <= 0:
            details["flash_attention"] = {
                "fallback": True,
                "reason": "non-monotonic timing (link noise dominated)"}
            log("  flash attention: timing unreliable (long chain not "
                "slower than short); no TFLOP/s claimed")
            return
        # useful causal flops: 4*b*h*s^2*d * 1/2
        aflops = 4 * b_ * h_ * s_ * s_ * d_ * 0.5 / per_call
        results["flash_attention_tflops"] = aflops / 1e12
        log(f"  flash attention: {aflops/1e12:.1f} TFLOP/s "
            f"(causal, b{b_} s{s_} h{h_} d{d_})")
    except Exception as e:
        log(f"  flash attention skipped: {e}")


# ---- LLM continuous-batching decode throughput (single chip) -------------
def _bench_serve_decode_e2e(details: dict):
    """End-to-end streaming decode vs isolated engine (smoke only; README
    "Serving hot loop"): 4 concurrent SSE clients stream greedy
    generations through proxy -> replica -> token ring, against the same
    4-way concurrent submit().tokens() drain on an engine living in THIS
    process. Legs interleave in alternating pairs and the gate rides the
    ratio of medians (the PR 12 noise-aware estimator's shape): on a
    1-core box both legs share the machine, so only a sustained shift —
    the actual serving overhead — moves the ratio."""
    import json as _json
    import socket
    import statistics
    import threading
    import urllib.request

    n_clients = 4
    max_tokens = 96
    lcfg_kw = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                   max_seq=256)

    try:
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.llm import LLMConfig
        from ray_tpu.llm.engine import ContinuousEngine, SamplingParams
        from ray_tpu.llm.openai import build_openai_app

        ray_tpu.init(num_cpus=4)
        eng = ContinuousEngine(LLMConfig(**lcfg_kw), max_batch=8,
                               decode_chunk=8)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        app = build_openai_app(LLMConfig(**lcfg_kw), max_batch=8,
                               decode_chunk=8)
        serve.run(app, route_prefix="/", port=port)
        base = f"http://127.0.0.1:{port}"
        sse_body = _json.dumps({"prompt": "bench", "max_tokens": max_tokens,
                                "temperature": 0.0, "stream": True}).encode()

        def engine_clients() -> int:
            done = [0] * n_clients

            def run(i):
                toks = eng.submit(
                    [1, 2, 3], SamplingParams(temperature=0.0,
                                              max_tokens=max_tokens)).tokens()
                done[i] = len(toks)

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(n_clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            return sum(done)

        ttfts: list[float] = []  # seconds to first token, every SSE leg

        def sse_clients() -> int:
            done = [0] * n_clients

            def run(i):
                req = urllib.request.Request(
                    f"{base}/v1/completions", data=sse_body,
                    headers={"Content-Type": "application/json"})
                n = 0
                t0 = time.perf_counter()
                with urllib.request.urlopen(req, timeout=300) as r:
                    for line in r:
                        line = line.decode().strip()
                        if not line.startswith("data: "):
                            continue
                        if line[6:] == "[DONE]":
                            break
                        if n == 0:
                            ttfts.append(time.perf_counter() - t0)
                        n += len(_json.loads(line[6:]).get("token_ids", []))
                done[i] = n

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(n_clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            return sum(done)

        def leg(fn) -> float:
            t0 = time.perf_counter()
            total = fn()
            dt = time.perf_counter() - t0
            if total < n_clients * max_tokens:
                raise RuntimeError(
                    f"leg lost tokens: {total} < {n_clients * max_tokens}")
            return total / dt

        # Warm BOTH engines (driver-local + replica: prefill bucket, every
        # greedy chunk program incl. the shrinking tail sizes) before any
        # timed window — a compile landing inside a leg corrupts it.
        engine_clients()
        sse_clients()

        eng_rates: list[float] = []
        e2e_rates: list[float] = []
        pairs = 3
        pair = 0
        while True:
            for _ in range(pairs):
                order = ((True, False) if pair % 2 == 0 else (False, True))
                for is_eng in order:
                    (eng_rates if is_eng else e2e_rates).append(
                        leg(engine_clients if is_eng else sse_clients))
                pair += 1
            eng_med = statistics.median(eng_rates)
            e2e_med = statistics.median(e2e_rates)
            ratio = e2e_med / max(eng_med, 1e-9)
            devs = ([abs(r / max(eng_med, 1e-9) - 1.0) for r in eng_rates]
                    + [abs(r / max(e2e_med, 1e-9) - 1.0) for r in e2e_rates])
            rel_mad = statistics.median(devs)
            # 0.5x is the spec'd floor, enforced whenever the box can
            # resolve it; ambient noise widens it downward the same way
            # the overhead lanes widen their 1.05x upward.
            bound = round(min(0.5, 0.5 / (1.0 + 3.0 * rel_mad)), 3)
            if ratio >= bound or pair >= 2 * pairs:
                break
            log(f"  serve_decode_e2e read {ratio:.3f}x over {pair} pairs "
                f"— extending the measurement window")
        serve.shutdown()
        eng.shutdown()
        ray_tpu.shutdown()
    except Exception as e:
        log(f"  serve_decode_e2e skipped: {e}")
        try:
            import ray_tpu

            ray_tpu.shutdown()
        except Exception:
            pass
        return
    log(f"  serve_decode_e2e: engine {eng_med:,.0f} tok/s vs end-to-end "
        f"{e2e_med:,.0f} tok/s ({ratio:.3f}x, {n_clients} SSE clients, "
        f"median of {pair} interleaved pairs; gate bound {bound:.3f}x)")
    details["serve_decode_engine_tok_s"] = round(eng_med, 1)
    details["serve_decode_e2e_tok_s"] = round(e2e_med, 1)
    details["serve_decode_e2e_ratio"] = round(ratio, 3)
    details["serve_decode_e2e_bound"] = bound
    if ttfts:
        details["serve_decode_ttft_p50_ms"] = round(
            _percentile(ttfts, 50) * 1e3, 1)
        details["serve_decode_ttft_p99_ms"] = round(
            _percentile(ttfts, 99) * 1e3, 1)


# ---- pipeline-parallel decode A/B (smoke only) ---------------------------
def _bench_llm_pipeline_decode(details: dict):
    """Pipeline-parallel decode vs single-process decode (smoke only;
    README "Pipeline-parallel serving"): 8 concurrent greedy generations
    on a 2-stage PipelinedEngine (microbatched compiled-DAG invocations,
    activations on device-object edges) against the SAME model — matched
    total parameters — in one ContinuousEngine. Legs interleave in
    alternating pairs; the gate rides the ratio of medians.

    The throughput bound is CORE-AWARE: with >= 2 cores per stage the
    pipeline must beat single-process by 1.3x (two stages decode two
    microbatches concurrently); a 1-core box time-slices both stage
    processes and the bound degrades to a sanity floor (the pipeline's
    plumbing — channels, placeholder pins, per-invocation dispatch — must
    stay within ~5x of the in-process engine even with zero parallelism
    available). The zero-RPC proof does not depend on cores: over the
    measured window the stages' resolve counters must show placeholder
    pins flowing and ZERO export/fetch RPCs."""
    import statistics
    import threading

    n_clients = 8
    max_tokens = 96
    lcfg_kw = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                   max_seq=256)

    try:
        import ray_tpu
        from ray_tpu.llm import LLMConfig
        from ray_tpu.llm.engine import ContinuousEngine, SamplingParams
        from ray_tpu.llm.pipeline import PipelinedEngine

        ray_tpu.init(num_cpus=4)
        single = ContinuousEngine(LLMConfig(**lcfg_kw), max_batch=8,
                                  decode_chunk=8)
        # microbatch=4 keeps the decode activation [4, 1, 64] f32 at the
        # 1KiB device-edge threshold, so every activation edge carries a
        # placeholder (the zero-RPC assertion below proves the resolves
        # all land in the local store).
        pipe = PipelinedEngine(LLMConfig(**lcfg_kw), n_stages=2,
                               max_batch=8, microbatch=4)

        def clients(eng) -> int:
            done = [0] * n_clients

            def run(i):
                done[i] = len(eng.submit(
                    [1, 2, 3], SamplingParams(
                        temperature=0.0, max_tokens=max_tokens)).tokens())

            ts = [threading.Thread(target=run, args=(i,))
                  for i in range(n_clients)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=300)
            return sum(done)

        def leg(eng) -> float:
            t0 = time.perf_counter()
            total = clients(eng)
            dt = time.perf_counter() - t0
            if total < n_clients * max_tokens:
                raise RuntimeError(
                    f"leg lost tokens: {total} < {n_clients * max_tokens}")
            return total / dt

        clients(single)  # warm: prefill buckets + every chunk program
        clients(pipe)    # warm: stage jits + channel loops
        pipe.reset_pipeline_stats()  # zero-RPC window starts AFTER warmup

        single_rates: list[float] = []
        pipe_rates: list[float] = []
        pairs = 3
        pair = 0
        while True:
            for _ in range(pairs):
                order = ((True, False) if pair % 2 == 0 else (False, True))
                for is_single in order:
                    (single_rates if is_single else pipe_rates).append(
                        leg(single if is_single else pipe))
                pair += 1
            single_med = statistics.median(single_rates)
            pipe_med = statistics.median(pipe_rates)
            ratio = pipe_med / max(single_med, 1e-9)
            devs = ([abs(r / max(single_med, 1e-9) - 1.0)
                     for r in single_rates]
                    + [abs(r / max(pipe_med, 1e-9) - 1.0)
                       for r in pipe_rates])
            rel_mad = statistics.median(devs)
            cores = os.cpu_count() or 1
            base = 1.3 if cores >= 4 else 0.2
            bound = round(min(base, base / (1.0 + 3.0 * rel_mad)), 3)
            if ratio >= bound or pair >= 2 * pairs:
                break
            log(f"  llm_pipeline_decode read {ratio:.3f}x over {pair} "
                f"pairs — extending the measurement window")
        stats = pipe.pipeline_stats()
        pipe.shutdown()
        single.shutdown()
        ray_tpu.shutdown()
    except Exception as e:
        log(f"  llm_pipeline_decode skipped: {e}")
        try:
            import ray_tpu

            ray_tpu.shutdown()
        except Exception:
            pass
        return
    log(f"  llm_pipeline_decode: single {single_med:,.0f} tok/s vs "
        f"2-stage pipeline {pipe_med:,.0f} tok/s ({ratio:.3f}x on "
        f"{os.cpu_count()} core(s); gate bound {bound:.3f}x; "
        f"{stats['edge_pins']} placeholder pins, "
        f"{stats['resolve_rpcs']} resolve RPCs)")
    details["llm_pipeline_single_tok_s"] = round(single_med, 1)
    details["llm_pipeline_tok_s"] = round(pipe_med, 1)
    details["llm_pipeline_ratio"] = round(ratio, 3)
    details["llm_pipeline_bound"] = bound
    details["llm_pipeline_stages"] = 2
    details["llm_pipeline_edge_pins"] = int(stats["edge_pins"])
    details["llm_pipeline_store_hits"] = int(stats["store_hits"])
    details["llm_pipeline_resolve_rpcs"] = int(stats["resolve_rpcs"])


def _bench_serve_overload(details: dict):
    """Overload & admission control lane (smoke only; README "Overload &
    admission control"). Two measurements:

    1. serve_admission A/B — handle-path requests/s with the admission
       plane armed vs RT_SERVE_ADMISSION=0 on the SAME cluster (the env
       flip switches the router's assign path, which is where the
       admission cost lives), through the shared interleaved-pairs
       estimator: admission must be free when budgets aren't binding.
    2. serve_overload storm — dozens of SSE clients with heavy-tailed
       lengths at ~10x a capped LLM deployment's capacity: every client
       must RESOLVE (admitted stream or typed shed), queue-full sheds
       must return in milliseconds (well under one decode-chunk
       interval), and admitted streams must make goodput.
    """
    import json as _json
    import socket
    import statistics
    import threading
    import urllib.error
    import urllib.request

    try:
        import ray_tpu
        from ray_tpu import serve

        # --- 1. admission on/off A/B on the handle path ------------------
        ray_tpu.init(num_cpus=4)

        @serve.deployment(max_ongoing_requests=64)
        def _echo(request=None):
            return 0

        handle = serve.run(_echo.bind(), route_prefix="/echo",
                           port=_free_port_bench())
        handle.remote().result(timeout_s=60)  # warm

        n_req = 150
        saved = os.environ.get("RT_SERVE_ADMISSION")

        def run_once(leg_on: bool) -> float:
            # The driver resolves RT_* env at access time: flipping it
            # here swaps the router between the admission queue and the
            # byte-identical legacy path without restarting the cluster.
            os.environ["RT_SERVE_ADMISSION"] = "1" if leg_on else "0"
            try:
                t0 = time.perf_counter()
                for _ in range(n_req):
                    if handle.remote().result(timeout_s=60) != 0:
                        raise RuntimeError("echo mismatch")
                return n_req / (time.perf_counter() - t0)
            finally:
                if saved is None:
                    os.environ.pop("RT_SERVE_ADMISSION", None)
                else:
                    os.environ["RT_SERVE_ADMISSION"] = saved

        _ab_overhead_lane("serve_admission", run_once, details, pairs=2)
        serve.shutdown()

        # --- 2. overload storm against a capped LLM deployment -----------
        from ray_tpu.llm import LLMConfig
        from ray_tpu.llm.openai import build_openai_app

        app = build_openai_app(
            LLMConfig(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                      max_seq=256),
            max_batch=4, decode_chunk=4, max_ongoing_requests=4,
            max_queued_requests=8, queue_deadline_s=1.5)
        port = _free_port_bench()
        serve.run(app, route_prefix="/", port=port)
        base = f"http://127.0.0.1:{port}"
        warm = _json.dumps({"prompt": "bench", "max_tokens": 2,
                            "temperature": 0.0}).encode()
        urllib.request.urlopen(urllib.request.Request(
            f"{base}/v1/completions", data=warm,
            headers={"Content-Type": "application/json"}),
            timeout=300).read()

        # Warm the CONCURRENT shapes too: batch sizes 1..4 each compile a
        # fresh program, and a compile landing mid-storm would hold the
        # executing slots past the queue deadline and starve admission.
        def _warm_stream():
            body = _json.dumps({"prompt": "bench", "max_tokens": 8,
                                "temperature": 0.0,
                                "stream": True}).encode()
            urllib.request.urlopen(urllib.request.Request(
                f"{base}/v1/completions", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=300).read()

        wts = [threading.Thread(target=_warm_stream, daemon=True)
               for _ in range(4)]
        for t in wts:
            t.start()
        for t in wts:
            t.join(timeout=300)

        n_clients = 40  # vs capacity 4 executing + 8 queued: ~10x load
        # Heavy-tailed lengths: mostly short, a few long stragglers.
        lengths = ([8] * 30 + [32] * 8 + [96] * 2)
        results: list[tuple] = []
        lock = threading.Lock()

        def client(i: int):
            t0 = time.perf_counter()
            body = _json.dumps({"prompt": "bench",
                                "max_tokens": lengths[i],
                                "temperature": 0.0,
                                "stream": True}).encode()
            req = urllib.request.Request(
                f"{base}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            try:
                n = 0
                with urllib.request.urlopen(req, timeout=120) as r:
                    for line in r:
                        line = line.decode().strip()
                        if not line.startswith("data: "):
                            continue
                        if line[6:] == "[DONE]":
                            break
                        n += len(_json.loads(line[6:]).get(
                            "token_ids", []))
                out = ("ok", n, time.perf_counter() - t0)
            except urllib.error.HTTPError as e:
                e.read()
                out = ("shed", e.code, time.perf_counter() - t0)
            except Exception as e:
                out = ("err", repr(e), time.perf_counter() - t0)
            with lock:
                results.append(out)

        t0 = time.perf_counter()
        ts = [threading.Thread(target=client, args=(i,), daemon=True)
              for i in range(n_clients)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=180)
        wall = time.perf_counter() - t0
        serve.shutdown()
        ray_tpu.shutdown()

        ok = [r for r in results if r[0] == "ok"]
        shed = [r for r in results if r[0] == "shed"]
        errs = [r for r in results if r[0] == "err"]
        if len(results) != n_clients or errs:
            raise RuntimeError(
                f"storm left {n_clients - len(results)} hung / "
                f"{len(errs)} untyped clients: {errs[:3]}")
        # 429s are immediate sheds (queue full / replica busy); 503s
        # waited out the 1.5s queue deadline. Both are RESOLUTIONS.
        fast_ms = sorted((r[2] * 1000.0 for r in shed if r[1] == 429))
        tokens = sum(r[1] for r in ok)
    except Exception as e:
        log(f"  serve_overload skipped: {e}")
        try:
            import ray_tpu

            ray_tpu.shutdown()
        except Exception:
            pass
        return
    log(f"  serve_overload: {len(ok)}/{n_clients} admitted, "
        f"{len(shed)} shed ({len(fast_ms)} fast), "
        f"{tokens / max(wall, 1e-9):,.0f} tok/s goodput over {wall:.1f}s"
        + (f"; fast-shed p50 {statistics.median(fast_ms):.0f}ms"
           if fast_ms else ""))
    details["serve_overload_clients"] = n_clients
    details["serve_overload_resolved"] = len(results)
    details["serve_overload_admitted"] = len(ok)
    details["serve_overload_shed_total"] = len(shed)
    if fast_ms:
        details["serve_overload_shed_ms_p50"] = round(
            statistics.median(fast_ms), 1)
    details["serve_overload_goodput_tok_s"] = round(
        tokens / max(wall, 1e-9), 1)
    if shed:
        details["serve_overload_shed_s_max"] = round(
            max(r[2] for r in shed), 2)


def _bench_serve_fanout(details: dict):
    """Cross-host token streaming + multi-proxy fan-out lane (smoke only;
    README "Cross-host streaming & multi-proxy"). Two measurements, both
    driving the same 16-client heavy-tailed SSE storm:

    1. push vs per-item — RT_STREAM_FORCE_PUSH=1 makes every replica skip
       the shm ring attach, so the handshake exercises exactly what a
       remote-host replica would: the push-stream transport (RT_STREAM_PUSH
       =1) vs the classic one-ObjectRef-per-item reply path (=0). Each leg
       is a full cluster lifecycle — the knobs are read replica-side, and
       workers inherit env at spawn. The gate is core-aware: where the
       proxy, replicas, and clients actually get cores the push transport
       must beat per-item by 1.5x; a 1-core box time-slices everything and
       the floor degrades to a sanity bound.
    2. multi-proxy fan-out — the same storm spread round-robin across 2
       proxy processes vs 1 (same cluster, default shm transport):
       aggregate goodput through the fleet must hold against the single
       proxy (the replica-set is the bottleneck, the ingress must not be).

    TTFT p50/p99 ride the details from the push legs; the p99 bound is
    derived from serve_decode_e2e's recorded TTFT when present — an
    internet-scale ingress may queue, but it must never let a client sit
    unacknowledged."""
    import json as _json
    import statistics
    import threading
    import urllib.request

    lengths = [8] * 10 + [32] * 4 + [96] * 2  # heavy-tailed, 16 clients
    lcfg_kw = dict(vocab_size=384, d_model=64, n_layers=2, n_heads=4,
                   max_seq=256)
    ncpu = os.cpu_count() or 1

    def storm(bases: list, ttfts=None) -> float:
        """One 16-client storm round-robin across `bases`; returns tok/s.
        Every client must stream its full generation — a lost token is a
        lane failure, not a slow run."""
        out = [None] * len(lengths)

        def client(i):
            body = _json.dumps({"prompt": "bench",
                                "max_tokens": lengths[i],
                                "temperature": 0.0,
                                "stream": True}).encode()
            req = urllib.request.Request(
                f"{bases[i % len(bases)]}/v1/completions", data=body,
                headers={"Content-Type": "application/json"})
            n = 0
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=300) as r:
                for line in r:
                    line = line.decode().strip()
                    if not line.startswith("data: "):
                        continue
                    if line[6:] == "[DONE]":
                        break
                    ev = _json.loads(line[6:])
                    if "error" in ev:
                        raise RuntimeError(f"SSE error event: {ev}")
                    if n == 0 and ttfts is not None:
                        ttfts.append(time.perf_counter() - t0)
                    n += len(ev.get("token_ids", []))
            out[i] = n

        t0 = time.perf_counter()
        ts = [threading.Thread(target=client, args=(i,), daemon=True)
              for i in range(len(lengths))]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=300)
        wall = time.perf_counter() - t0
        if any(o is None for o in out):
            raise RuntimeError("storm left clients hung or errored")
        total = sum(out)
        if total < sum(lengths):
            raise RuntimeError(f"storm lost tokens: {total} < {sum(lengths)}")
        return total / wall

    def cycle(env: dict, n_proxies: int, ttfts=None, storms: int = 2):
        """One full cluster lifecycle under `env`: init, deploy, warm every
        chunk program AND the transport, measure, tear down. The env must
        be set BEFORE init — replica/proxy processes inherit it at spawn."""
        import ray_tpu
        from ray_tpu import serve
        from ray_tpu.llm import LLMConfig
        from ray_tpu.llm.openai import build_openai_app

        saved = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            ray_tpu.init(num_cpus=4)
            port = _free_port_bench()
            app = build_openai_app(LLMConfig(**lcfg_kw), max_batch=8,
                                   decode_chunk=8)
            serve.run(app, route_prefix="/", port=port,
                      num_proxies=n_proxies)
            if n_proxies > 1:
                bases = [f"http://127.0.0.1:{p}"
                         for p in sorted(serve.proxy_ports().values())]
            else:
                bases = [f"http://127.0.0.1:{port}"]
            storm(bases)  # warm
            rates = [storm(bases, ttfts) for _ in range(storms)]
            serve.shutdown()
            return statistics.median(rates)
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
            ray_tpu.shutdown()

    try:
        # --- 1. push-stream vs per-item fallback (force-push legs) -------
        push_env = {"RT_STREAM_FORCE_PUSH": "1", "RT_STREAM_PUSH": "1"}
        item_env = {"RT_STREAM_FORCE_PUSH": "1", "RT_STREAM_PUSH": "0"}
        # One lifecycle per leg (each medians 2 storms after a warm storm):
        # a lifecycle is ~30s of init+compile, so rounds are spent inside
        # the leg, not on more legs.
        push_ttfts: list = []
        push_med = cycle(push_env, 1, push_ttfts)
        item_med = cycle(item_env, 1)
        push_ratio = push_med / max(item_med, 1e-9)
        push_bound = 1.5 if ncpu >= 4 else 0.6

        # --- 2. multi-proxy fan-out vs single proxy (shm transport) ------
        multi_med = cycle({}, 2)
        single_med = cycle({}, 1)
        multi_ratio = multi_med / max(single_med, 1e-9)
        multi_bound = 0.9 if ncpu >= 4 else 0.6

        ttft_p50 = _percentile(push_ttfts, 50) * 1e3
        ttft_p99 = _percentile(push_ttfts, 99) * 1e3
        # An overloaded ingress may queue, but p99 TTFT stays bounded
        # relative to the lightly-loaded serve_decode_e2e baseline (or an
        # absolute floor when that lane didn't record one).
        ttft_bound = max(5000.0,
                         20.0 * details.get("serve_decode_ttft_p99_ms",
                                            250.0))
    except Exception as e:
        log(f"  serve_fanout skipped: {e}")
        try:
            import ray_tpu

            ray_tpu.shutdown()
        except Exception:
            pass
        return
    log(f"  serve_fanout: push-stream {push_med:,.0f} tok/s vs per-item "
        f"{item_med:,.0f} tok/s ({push_ratio:.2f}x, bound {push_bound}x); "
        f"2-proxy {multi_med:,.0f} tok/s vs 1-proxy {single_med:,.0f} "
        f"tok/s ({multi_ratio:.2f}x, bound {multi_bound}x); "
        f"TTFT p50 {ttft_p50:.0f}ms p99 {ttft_p99:.0f}ms")
    details["serve_fanout_push_tok_s"] = round(push_med, 1)
    details["serve_fanout_peritem_tok_s"] = round(item_med, 1)
    details["serve_fanout_push_ratio"] = round(push_ratio, 3)
    details["serve_fanout_push_bound"] = push_bound
    details["serve_fanout_multi_tok_s"] = round(multi_med, 1)
    details["serve_fanout_single_tok_s"] = round(single_med, 1)
    details["serve_fanout_multi_ratio"] = round(multi_ratio, 3)
    details["serve_fanout_multi_bound"] = multi_bound
    details["serve_fanout_ttft_p50_ms"] = round(ttft_p50, 1)
    details["serve_fanout_ttft_p99_ms"] = round(ttft_p99, 1)
    details["serve_fanout_ttft_p99_bound_ms"] = round(ttft_bound, 1)


def _bench_data_shuffle(details: dict):
    """Streaming shuffle A/B (smoke only; README "Data plane"): the SAME
    8-block random_shuffle through the exchange plane with pipelined
    consolidation on vs off (RT_DATA_PIPELINED_EXCHANGE env flip — the
    driver reads the knob per exchange, so one cluster serves both legs),
    measured in MB/s through the interleaved-medians estimator. The perf
    gate (tests/test_perf_smoke.py) asserts speedup >= the core-aware
    floor recorded here: 1.5x barrier where map and consolidation tasks
    can actually overlap (>= 4 cores); on a 1-core box the pipelined
    mode's extra consolidation hops are pure overhead and the floor is a
    noise-widened sanity bound. A single-process numpy take()-style
    shuffle of the same rows anchors the GB/s numbers."""
    import ray_tpu
    from ray_tpu import data as rd

    n_blocks, rows_per, row_bytes = 8, 16, 128 << 10
    items = [os.urandom(row_bytes) for _ in range(n_blocks * rows_per)]
    total_mb = len(items) * row_bytes / 1e6
    prev = {k: os.environ.pop(k, None)
            for k in ("RT_DATA_PIPELINED_EXCHANGE", "RT_DATA_REDUCE_FANIN")}
    # Half the map count: consolidations must fire mid-wave, not only at
    # the tail, for the pipelined leg to express any overlap.
    os.environ["RT_DATA_REDUCE_FANIN"] = "4"
    seed = [0]

    def run_once(pipelined: bool) -> float:
        os.environ["RT_DATA_PIPELINED_EXCHANGE"] = "1" if pipelined else "0"
        reps = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < max(MIN_TIME, 0.5) or reps == 0:
            seed[0] += 1
            refs = rd.from_items(items, parallelism=n_blocks).random_shuffle(
                seed=seed[0])._block_refs()
            # wait() forces the full exchange (maps, consolidations,
            # finalizes) to completion without pulling a payload row to
            # the driver — the lane times the exchange, not a driver gather.
            ray_tpu.wait(refs, num_returns=len(refs), timeout=300)
            reps += 1
        return reps * total_mb / (time.perf_counter() - t0)

    try:
        ray_tpu.init(num_cpus=4)
        try:
            # Warm the worker pool and pin correctness once before timing.
            os.environ["RT_DATA_PIPELINED_EXCHANGE"] = "1"
            warm = rd.from_items(items, parallelism=n_blocks).random_shuffle(
                seed=0)
            if warm.count() != len(items):
                raise RuntimeError("shuffle dropped rows")
            _ab_overhead_lane("data_shuffle", run_once, details)
        finally:
            ray_tpu.shutdown()
    except Exception as e:
        log(f"  data_shuffle skipped: {e}")
        return
    finally:
        for k, v in prev.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    barrier = details.pop("data_shuffle_off_tasks_s", None)
    pipelined = details.pop("data_shuffle_on_tasks_s", None)
    details.pop("data_shuffle_off_best_tasks_s", None)
    details.pop("data_shuffle_overhead", None)
    bound = details.pop("data_shuffle_overhead_bound", None) or 1.05
    if not barrier or not pipelined:
        return
    speedup = pipelined / max(barrier, 1e-9)
    cores = os.cpu_count() or 1
    floor = 1.5 if cores >= 4 else round(min(0.5, 1.0 / bound), 3)
    # Single-process pandas-style baseline: one permutation take() over
    # the same bytes in one address space — no pickling, no IPC. The
    # distributed plane is not expected to win on one host; the floor
    # pins "moves data at a real fraction of local speed" per core class.
    mat = np.frombuffer(b"".join(items), dtype=np.uint8).reshape(
        len(items), row_bytes)
    rng = np.random.default_rng(0)
    local_reps = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < 0.3:
        mat[rng.permutation(len(items))]
        local_reps += 1
    local_mb = local_reps * total_mb / (time.perf_counter() - t0)
    del mat
    details["data_shuffle_gbps"] = round(pipelined / 1000, 3)
    details["data_shuffle_barrier_gbps"] = round(barrier / 1000, 3)
    details["data_shuffle_speedup"] = round(speedup, 3)
    details["data_shuffle_speedup_floor"] = floor
    details["data_shuffle_local_gbps"] = round(local_mb / 1000, 3)
    details["data_shuffle_vs_local"] = round(pipelined / max(local_mb, 1e-9), 4)
    details["data_shuffle_vs_local_floor"] = 0.05 if cores >= 4 else 0.005
    log(f"  data_shuffle: pipelined {pipelined / 1000:.3f} GB/s vs barrier "
        f"{barrier / 1000:.3f} GB/s ({speedup:.2f}x, floor {floor}x; local "
        f"numpy take() {local_mb / 1000:.2f} GB/s)")


def _bench_data_ingest(details: dict):
    """Streaming ingest (smoke only; README "Data plane"): end-to-end
    Dataset.iter_batches over a fresh range_tensor dataset each rep —
    read tasks execute under the in-flight window while the driver
    consumes numpy batches, never materializing the whole dataset.
    Reported as data_ingest_gbps; the perf gate is a moves-data-at-all
    sanity floor (the lane pins the streamed path end to end, it does
    not race memcpy)."""
    import ray_tpu
    from ray_tpu import data as rd

    rows, dim = 1 << 14, 128  # 16 MB of int64 rows over 8 blocks
    total_gb = rows * dim * 8 / 1e9

    def consume_once():
        nbytes = 0
        ds = rd.range_tensor(rows, shape=(dim,), parallelism=8)
        for b in ds.iter_batches(batch_size=2048, batch_format="numpy"):
            nbytes += b["data"].nbytes
        if nbytes != rows * dim * 8:
            raise RuntimeError(f"ingest dropped rows ({nbytes} bytes)")

    try:
        ray_tpu.init(num_cpus=4)
        try:
            consume_once()  # warm the worker pool
            reps = 0
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < max(MIN_TIME, 0.5) or reps == 0:
                consume_once()
                reps += 1
            dt = time.perf_counter() - t0
        finally:
            ray_tpu.shutdown()
    except Exception as e:
        log(f"  data_ingest skipped: {e}")
        return
    gbps = reps * total_gb / dt
    details["data_ingest_gbps"] = round(gbps, 3)
    log(f"  data_ingest: {gbps:.3f} GB/s streamed through iter_batches "
        f"({reps} x {total_gb * 1000:.0f} MB)")


def _free_port_bench() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _percentile(vals: list, pct: float) -> float:
    """Nearest-rank percentile on a copy (small-N latency samples)."""
    xs = sorted(vals)
    k = max(0, min(len(xs) - 1, int(round(pct / 100.0 * len(xs) + 0.5)) - 1))
    return xs[k]


def _bench_llm_decode(results: dict):
    try:
        import jax

        if jax.devices()[0].platform != "tpu":
            return
        from ray_tpu.llm import LLMConfig
        from ray_tpu.llm.engine import ContinuousEngine, SamplingParams

        lcfg = LLMConfig(vocab_size=32000, d_model=1024, n_layers=8,
                         n_heads=16, max_seq=1024, dtype="bfloat16")
        eng = ContinuousEngine(lcfg, max_batch=8, decode_chunk=16)
        rng = np.random.RandomState(0)
        sp = SamplingParams(temperature=0.0, max_tokens=128)

        def churn(n_reqs):
            """Mixed batch churn: staggered submits with varied prompt
            lengths — requests join/leave the running batch (the
            continuous-batching case, not lockstep generate)."""
            streams = []
            total = 0
            for i in range(n_reqs):
                plen = int(rng.choice([64, 128, 256]))
                smp = SamplingParams(temperature=0.0,
                                     max_tokens=96 + 16 * (i % 3))
                streams.append(eng.submit(
                    rng.randint(0, 32000, size=plen), smp))
                total += smp.max_tokens
            for s in streams:
                s.tokens()
            return total

        # Warm EVERY prefill bucket the timed churn can draw (each
        # bucket is its own compiled program; one landing inside the
        # timed window would corrupt the number), then a churn for the
        # chunk-size programs.
        warm = [eng.submit(np.random.randint(0, 32000, size=p),
                           SamplingParams(temperature=0.0, max_tokens=8))
                for p in (64, 128, 256)]
        for s in warm:
            s.tokens()
        churn(8)  # warm: chunk sizes + admission interleavings
        t0 = time.perf_counter()
        total = churn(16)
        dt = time.perf_counter() - t0
        churn_tps = total / dt
        # Steady-state decode: chunks chained ON DEVICE, one readback —
        # the decode-throughput number (the r04 methodology measured a
        # single whole-generation scan the same way). The churn number
        # above additionally pays scheduler syncs, whose cost is the
        # HOST-LINK latency (hundreds of ms through a tunneled TPU,
        # ~1ms co-located).
        import jax.numpy as jnp

        cache = eng._init_cache()
        toks = jnp.zeros(8, jnp.int32)
        lens = jnp.full(8, 200, jnp.int32)
        zf = jnp.zeros(8, jnp.float32)
        zi = jnp.zeros(8, jnp.int32)
        of = jnp.ones(8, jnp.float32)

        def chain(n_chunks):
            nonlocal cache, toks, lens
            c, t, l = cache, toks, lens
            outs = []
            for _ in range(n_chunks):
                c, _k, out, l = eng._chunk(
                    eng.params, c, t, l, eng._keys, zf, zi, of, 16, True)
                t = out[:, -1]
                outs.append(out)
            t0 = time.perf_counter()
            np.asarray(jnp.concatenate(outs, axis=1))
            dt = time.perf_counter() - t0
            cache, toks, lens = c, t, l  # chunk donates its cache input
            return dt

        chain(1)
        t2 = min(chain(2) for _ in range(2))
        t10 = min(chain(10) for _ in range(2))
        per_step = max(1e-9, (t10 - t2) / (8 * 16))
        tps = 8 / per_step
        results["llm_decode_tokens_per_s"] = tps
        log(f"  llm decode: {tps:,.0f} tok/s steady (continuous-batch "
            f"engine, b8, bf16, 1024d x 8L; end-to-end churn with "
            f"host-link syncs: {churn_tps:,.0f} tok/s)")
        eng.shutdown()
    except Exception as e:
        log(f"  llm decode skipped: {e}")


# ---- RLlib PPO env-steps/sec (BASELINE north-star workload) --------------
def _bench_rllib_ppo(results: dict):
    try:
        from ray_tpu.rllib import PPOConfig

        algo = (PPOConfig()
                .environment("CartPole-v1")
                .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                             rollout_fragment_length=64)
                .build())
        algo.train()  # warm: jit compiles, runners spin up
        t0 = time.perf_counter()
        steps = sum(algo.train()["num_env_steps_sampled"] for _ in range(5))
        rate = steps / (time.perf_counter() - t0)
        results["ppo_env_steps_per_s"] = rate
        log(f"  rllib ppo: {rate:,.0f} env-steps/s (CartPole, 2 runners)")
        algo.stop()
    except Exception as e:
        log(f"  rllib ppo skipped: {e}")


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tasks/actors/objects only, short windows (<30s), "
                         "no TPU/LLM/RLlib sections")
    args = ap.parse_args()
    main(smoke=args.smoke)
