"""Tune: search spaces, Tuner.fit over trial actors, ASHA early stopping,
PBT exploit/explore, trainer-in-tuner.

reference tests: python/ray/tune/tests/test_tune_restore.py,
test_trial_scheduler.py (ASHA), test_trial_scheduler_pbt.py,
test_tuner.py.
"""

import os
import pickle
import tempfile

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig
from ray_tpu.tune import ASHAScheduler, PopulationBasedTraining, TuneConfig, Tuner


def test_search_space_generation():
    from ray_tpu.tune.search import BasicVariantGenerator

    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.choice([1, 2, 3]),
        "nested": {"h": tune.grid_search([16, 32])},
        "const": 7,
    }
    cfgs = BasicVariantGenerator(seed=0).generate(space, num_samples=3)
    assert len(cfgs) == 12  # 2 x 2 grid combos x 3 samples
    assert all(c["const"] == 7 for c in cfgs)
    assert {(c["lr"], c["nested"]["h"]) for c in cfgs} == {
        (0.1, 16), (0.1, 32), (0.01, 16), (0.01, 32)}
    assert all(c["wd"] in (1, 2, 3) for c in cfgs)


def _quadratic(config):
    """Best score at x=3."""
    for it in range(8):
        score = -(config["x"] - 3.0) ** 2 - it * 0.0  # constant per trial
        tune.report({"score": score})


def test_tuner_grid_fifo(ray_start_4cpu, tmp_path):
    tuner = Tuner(
        _quadratic,
        param_space={"x": tune.grid_search([0.0, 2.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    assert grid.num_errors == 0
    best = grid.get_best_result()
    assert best.config["x"] == 3.0
    assert best.metrics["score"] == 0.0
    # every trial ran all 8 iterations under FIFO
    assert all(r.metrics["training_iteration"] == 8 for r in grid)


def test_tuner_trial_error_reported(ray_start_2cpu, tmp_path):
    def boom(config):
        if config["x"] == 1:
            raise RuntimeError("kaboom")
        tune.report({"score": 1.0})

    grid = Tuner(
        boom, param_space={"x": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert grid.num_errors == 1
    ok = [r for r in grid if r.error is None]
    assert len(ok) == 1 and ok[0].metrics["score"] == 1.0


def _staircase(config):
    """Good trials (high base) keep improving; bad trials plateau low.
    A KV barrier aligns all trials before the loop so ASHA's rungs see the
    full population regardless of actor spawn stagger."""
    import time as _time
    import uuid

    from ray_tpu._private.worker import global_worker

    w = global_worker()
    w.kv("put", ns="test", key=f"asha_gate/{uuid.uuid4().hex}", value=b"1")
    while len(w.kv("keys", ns="test", prefix="asha_gate/")["keys"]) < config["world"]:
        _time.sleep(0.02)
    # Weak trials iterate 10x slower: strong trials populate every rung
    # before a weak trial reaches it, making the ASHA cut deterministic
    # (async halving lets whoever reaches a rung first pass uncontested).
    pace = 0.03 if config["base"] > 5 else 0.3
    for it in range(1, 21):
        _time.sleep(pace)
        tune.report({"score": config["base"] + it * config["base"] * 0.1})


def test_asha_stops_bad_half(ray_start_cluster, tmp_path):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=7)  # head has 1 -> 8 CPUs total
    ray_tpu.init(address=cluster.address)

    # Strong trials interleaved FIRST: whatever the actor start order/pace,
    # every weak trial finds a strong score recorded at its first rung and
    # is cut there — deterministic even if trials end up running serially.
    bases = [10.0, 1.0, 10.0, 1.0, 10.0, 1.0, 10.0, 1.0]
    tuner = Tuner(
        _staircase,
        param_space={"base": tune.grid_search(bases), "world": 8},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=ASHAScheduler(max_t=20, grace_period=2,
                                    reduction_factor=2)),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 8 and grid.num_errors == 0
    iters = sorted(r.metrics["training_iteration"] for r in grid)
    # The strong half must reach max_t-ish; the weak half must be cut early.
    stopped_early = [i for i in iters if i < 20]
    assert len(stopped_early) >= 4, iters  # the weak half was cut
    best = grid.get_best_result()
    assert best.config["base"] == 10.0
    # the winner ran to the end
    assert best.metrics["training_iteration"] >= 19


def _pbt_loop(config):
    """Score grows by `rate` per step; checkpoint carries the accumulated
    score so exploit actually transfers progress. A KV start barrier keeps
    all trials concurrent — exploit decisions need live peers."""
    import time as _time
    import uuid as _uuid

    from ray_tpu._private.worker import global_worker

    w = global_worker()
    w.kv("put", ns="test", key=f"pbt_gate/{_uuid.uuid4().hex}", value=b"1")
    deadline = _time.monotonic() + 30
    while (len(w.kv("keys", ns="test", prefix="pbt_gate/")["keys"])
           < config["world"] and _time.monotonic() < deadline):
        _time.sleep(0.02)
    score = 0.0
    step = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with open(os.path.join(ckpt.path, "state.pkl"), "rb") as f:
            st = pickle.load(f)
        score, step = st["score"], st["step"]
    while step < 25:
        step += 1
        score += config["rate"]
        with tempfile.TemporaryDirectory() as d:
            with open(os.path.join(d, "state.pkl"), "wb") as f:
                pickle.dump({"score": score, "step": step}, f)
            tune.report({"score": score}, checkpoint=Checkpoint(d))


def test_pbt_exploits_good_trials(ray_start_4cpu, tmp_path):
    pbt = PopulationBasedTraining(
        perturbation_interval=5,
        hyperparam_mutations={"rate": tune.uniform(0.1, 10.0)},
        quantile_fraction=0.5, seed=0)  # bottom 2 of 4 exploit the top 2
    tuner = Tuner(
        _pbt_loop,
        param_space={"rate": tune.grid_search([0.1, 0.2, 5.0, 6.0]),
                     "world": 4},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt),
        run_config=RunConfig(storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0
    # The weak trials (rate 0.1/0.2 -> final ~2.5-5) must have exploited a
    # strong donor: every trial's final score should blow past the
    # no-exploit weak ceiling.
    finals = sorted(r.metrics["score"] for r in grid)
    assert finals[0] > 10.0, finals


def test_pbt_clone_pin_protects_donor_checkpoint(tmp_path):
    """The PBT checkpoint-sharing hazard: a clone restores from
    `donor.checkpoint_path`, so the donor's retention/GC must not collect
    that dir while the clone still references it. The controller pins the
    restore source on exploit; the pin defeats retention until the clone
    has a checkpoint of its own (or stops)."""
    import numpy as np

    from ray_tpu import storage
    from ray_tpu.train import checkpoint as ckpt_mod
    from ray_tpu.tune.trial import Trial
    from ray_tpu.tune.tuner import TuneController

    ctl = TuneController.__new__(TuneController)  # pin logic only
    donor = Trial({"rate": 5.0}, str(tmp_path / "trial_donor"))
    clone = Trial({"rate": 0.1}, str(tmp_path / "trial_clone"))
    # donor has one committed checkpoint; session-side retention keeps 1
    ck1 = storage.join(donor.trial_dir, "checkpoint_000001")
    ckpt_mod.upload_directory(_make_dir(tmp_path, "payload-1"), ck1, step=1)
    donor.checkpoint_path = ck1

    ctl._pin_restore_source(clone, donor.checkpoint_path)
    assert clone.restore_from == ck1 and clone.pinned_source == ck1

    # donor keeps training: a newer checkpoint + keep-last-1 retention
    # (what the donor's trial session runs under RT_CKPT_KEEP=1)
    ck2 = storage.join(donor.trial_dir, "checkpoint_000002")
    ckpt_mod.upload_directory(_make_dir(tmp_path, "payload-2"), ck2, step=2)
    deleted = ckpt_mod.retention(donor.trial_dir, keep=1)
    assert deleted == []  # ck1 pinned by the clone -> survives
    assert storage.exists(storage.join(ck1, "state.txt"))

    # the clone writes its own checkpoint -> controller releases the pin
    clone.checkpoint_path = storage.join(clone.trial_dir,
                                         "checkpoint_000001")
    ctl._release_restore_pin(clone)
    assert ckpt_mod.retention(donor.trial_dir, keep=1) == [ck1]
    assert not storage.exists(ck1)
    assert storage.exists(storage.join(ck2, "state.txt"))


def _make_dir(tmp_path, payload: str) -> str:
    import uuid

    d = tmp_path / f"src_{uuid.uuid4().hex[:6]}"
    d.mkdir()
    (d / "state.txt").write_text(payload)
    return str(d)


def test_trainer_in_tuner(ray_start_4cpu, tmp_path):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        import ray_tpu.train as train

        # trivial "training": the tuned lr decides the loss
        train.report({"loss": abs(config["lr"] - 0.01)})

    trainer = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(storage_path=str(tmp_path / "t")))
    grid = Tuner(
        trainer,
        param_space={"train_loop_config": {
            "lr": tune.grid_search([0.1, 0.01, 0.5])}},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(storage_path=str(tmp_path)),
    ).fit()
    assert grid.num_errors == 0
    assert grid.get_best_result().config["train_loop_config"]["lr"] == 0.01


def test_median_stopping_rule(ray_start_4cpu):
    """Bad trials stop early once enough peers establish the median
    (reference tune/schedulers/median_stopping_rule.py)."""
    from ray_tpu import tune
    from ray_tpu.tune import MedianStoppingRule, TuneConfig, Tuner

    def trainable(config):
        import time as _t

        for i in range(1, 21):
            _t.sleep(0.05)  # real iterations take time: peers interleave
            tune.report({"score": config["q"] * i, "training_iteration": i})

    tuner = Tuner(
        trainable,
        param_space={"q": tune.grid_search([1, 2, 10, 11, 12, 13])},
        tune_config=TuneConfig(
            metric="score", mode="max",
            scheduler=MedianStoppingRule(grace_period=3,
                                         min_samples_required=3)),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.config["q"] == 13
    iters = {r.config["q"]: r.metrics.get("training_iteration") for r in grid}
    # The clearly-below-median trials must have been cut early.
    assert iters[1] < 20 and iters[2] < 20, iters


def test_hyperband_scheduler(ray_start_4cpu):
    from ray_tpu import tune
    from ray_tpu.tune import HyperBandScheduler, TuneConfig, Tuner

    def trainable(config):
        import time as _t

        for i in range(1, 28):
            _t.sleep(0.04)
            tune.report({"loss": 100.0 / config["lr_id"] - i * 0.01,
                         "training_iteration": i})

    tuner = Tuner(
        trainable,
        param_space={"lr_id": tune.grid_search([1, 2, 3, 4, 5, 6])},
        tune_config=TuneConfig(
            metric="loss", mode="min",
            scheduler=HyperBandScheduler(max_t=27, reduction_factor=3)),
    )
    grid = tuner.fit()
    assert grid.get_best_result().config["lr_id"] == 6
    iters = {r.config["lr_id"]: r.metrics.get("training_iteration") for r in grid}
    assert iters[1] < 27, iters  # worst trial halved out before max_t


def test_tpe_searcher_converges(ray_start_4cpu):
    """Native TPE (the reference's OptunaSearch seat) beats random on a
    smooth objective: suggestions concentrate near the optimum once the
    startup trials are in."""
    from ray_tpu import tune
    from ray_tpu.tune import TPESearcher, TuneConfig, Tuner

    def objective(config):
        x, y = config["x"], config["y"]
        tune.report({"score": -(x - 0.3) ** 2 - (y - 7.0) ** 2})

    tuner = Tuner(
        objective,
        param_space={"x": tune.uniform(0.0, 1.0),
                     "y": tune.loguniform(1.0, 100.0)},
        tune_config=TuneConfig(metric="score", mode="max", num_samples=24,
                               max_concurrent_trials=4,
                               search_alg=TPESearcher(n_startup=6, seed=0)),
    )
    grid = tuner.fit()
    assert grid.num_errors == 0 and len(grid) == 24
    best = grid.get_best_result()
    assert best.metrics["score"] > -0.5, best.metrics
    # Later (model-guided) suggestions should be better than the random
    # startup phase on average.
    scores = [r.metrics["score"] for r in grid]
    import statistics

    # Medians: TPE keeps a uniform exploration component, so one late
    # outlier must not fail the direction-of-improvement check.
    assert statistics.median(scores[12:]) > statistics.median(scores[:6]), scores


def test_tuner_restore_resumes_experiment(ray_start_2cpu, tmp_path):
    """Tuner.restore finishes an interrupted experiment: completed trials
    keep results, unfinished ones re-run (reference tuner.py restore)."""
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner
    from ray_tpu.train import RunConfig
    from ray_tpu.train.config import FailureConfig

    marker = str(tmp_path / "fail_once")

    def flaky(config):
        # Trial with x == 3 kills ITSELF the first time (simulating an
        # interrupted experiment); every other trial finishes normally.
        if config["x"] == 3 and not os.path.exists(marker):
            open(marker, "w").close()
            os._exit(1)
        tune.report({"score": config["x"] * 10, "training_iteration": 1})

    exp_dir = str(tmp_path / "exp")
    tuner = Tuner(
        flaky,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=RunConfig(storage_path=str(tmp_path), name="exp",
                             failure_config=FailureConfig(max_failures=0)),
    )
    grid = tuner.fit()
    # trial x=3 errored (simulated interruption)
    assert grid.num_errors == 1
    # restore: the errored trial re-runs (marker exists now -> succeeds)
    tuner2 = Tuner.restore(exp_dir)
    grid2 = tuner2.fit()
    assert grid2.num_errors == 0 and len(grid2) == 3
    assert sorted(r.metrics["score"] for r in grid2) == [10, 20, 30]
