"""Host-tier collective library tests (reference
python/ray/util/collective/tests/): groups of actors rendezvous and run
allreduce/allgather/broadcast/barrier through the control plane."""

import numpy as np

import ray_tpu


def _worker_cls():
    @ray_tpu.remote
    class ColWorker:
        def __init__(self, world_size, rank, group):
            from ray_tpu.util import collective as col

            self.col = col
            self.rank = rank
            col.init_collective_group(world_size, rank, group)

        def allreduce(self, value, group):
            return self.col.allreduce(np.asarray(value, dtype=np.float32), group_name=group)

        def allgather(self, value, group):
            return self.col.allgather(np.asarray(value), group_name=group)

        def broadcast(self, value, group):
            return self.col.broadcast(value if self.rank == 0 else None,
                                      src_rank=0, group_name=group)

        def tree_allreduce(self, group):
            tree = {"a": np.full((2, 2), float(self.rank)), "b": np.ones(3) * self.rank}
            return self.col.allreduce(tree, group_name=group)

        def p2p(self, group):
            if self.rank == 0:
                self.col.send(np.arange(4), dst_rank=1, group_name=group)
                return None
            return self.col.recv(src_rank=0, group_name=group)

    return ColWorker


def test_collective_allreduce_allgather(ray_start_4cpu):
    ColWorker = _worker_cls()
    world = 3
    ws = [ColWorker.remote(world, r, "g1") for r in range(world)]
    out = ray_tpu.get([w.allreduce.remote([1.0, float(i)], "g1")
                       for i, w in enumerate(ws)], timeout=120)
    for o in out:
        np.testing.assert_allclose(o, [3.0, 0.0 + 1.0 + 2.0])
    gathered = ray_tpu.get([w.allgather.remote(i * 10, "g1")
                            for i, w in enumerate(ws)], timeout=120)
    for g in gathered:
        assert [int(x) for x in g] == [0, 10, 20]


def test_collective_broadcast_and_tree(ray_start_4cpu):
    ColWorker = _worker_cls()
    world = 2
    ws = [ColWorker.remote(world, r, "g2") for r in range(world)]
    out = ray_tpu.get([w.broadcast.remote("payload-from-0", "g2") for w in ws], timeout=120)
    assert out == ["payload-from-0", "payload-from-0"]
    trees = ray_tpu.get([w.tree_allreduce.remote("g2") for w in ws], timeout=120)
    for t in trees:
        np.testing.assert_allclose(t["a"], np.full((2, 2), 1.0))  # 0 + 1
        np.testing.assert_allclose(t["b"], np.ones(3))


def test_collective_p2p(ray_start_4cpu):
    ColWorker = _worker_cls()
    ws = [_w for _w in (_worker_cls().remote(2, r, "g3") for r in range(2))]
    out = ray_tpu.get([w.p2p.remote("g3") for w in ws], timeout=120)
    assert out[0] is None
    np.testing.assert_allclose(out[1], np.arange(4))
