"""Chaos: pipeline-parallel LLM decode under stage death (ISSUE 18,
README "Pipeline-parallel serving" failure contract).

SIGKILLing a stage actor mid-generation must (a) end EVERY open GenStream
with an attributed DagStageError naming the stage — never a hang on a
consumer draining tokens, (b) land the `dag_stage_death` event in the
PR 14 event plane, and (c) leave the engine SERVING: it tears the dead
graph down, rebuilds fresh stage actors, and a fresh generate() succeeds.
Consecutive-failure accounting resets on any completed invocation, so a
single chaos kill never eats into the rebuild budget of a later one."""

import os
import queue
import signal
import time

import pytest

import ray_tpu
from ray_tpu.exceptions import DagStageError
from ray_tpu.llm import LLMConfig
from ray_tpu.llm.engine import SamplingParams

DEADLINE_S = 25.0  # detection budget: runtime death detection + one poll

CFG_KW = dict(vocab_size=128, d_model=32, n_layers=2, n_heads=2,
              max_seq=256)


def _wait(pred, timeout=30.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = pred()
        if out:
            return out
        time.sleep(0.2)
    raise TimeoutError(f"timed out waiting for {what}")


def _drain_bounded(stream, budget_s=60.0) -> list:
    """Drain a GenStream with a hard wall — a hang is a test FAILURE with
    a named deadline, not a pytest timeout. Engine errors propagate."""
    toks = []
    deadline = time.monotonic() + budget_s
    while time.monotonic() < deadline:
        try:
            toks.append(stream.next(timeout=5))
        except queue.Empty:
            continue
        except StopIteration:
            return toks
    pytest.fail(f"stream did not finish within {budget_s}s "
                f"({len(toks)} tokens seen) — shed-not-stall is broken")


def test_stage_sigkill_attributes_streams_then_engine_rebuilds(
        ray_start_4cpu):
    from ray_tpu.llm.pipeline import PipelinedEngine
    from ray_tpu.util import state

    pipe = PipelinedEngine(LLMConfig(**CFG_KW), n_stages=2, max_batch=4,
                           microbatch=2)
    try:
        # Healthy steady state first (also arms the rebuild-budget reset:
        # completed invocations zero the consecutive-failure count).
        warm = pipe.generate([[1, 2]], SamplingParams(temperature=0.0,
                                                      max_tokens=4))
        assert len(warm[0]) == 4

        dag_id = pipe._dag.dag_id
        # Kill the LAST stage: its death must propagate upstream through
        # the driver's head-of-line wait, not just break its own edge.
        victim_pid = ray_tpu.get(pipe._actors[-1].pid.remote(), timeout=30)

        streams = [pipe.submit([1, 2, 3],
                               SamplingParams(temperature=0.0,
                                              max_tokens=200))
                   for _ in range(3)]
        # Mid-generation for real: first token out before the kill.
        streams[0].next(timeout=30)
        t0 = time.monotonic()
        os.kill(victim_pid, signal.SIGKILL)

        for s in streams:
            with pytest.raises(DagStageError) as ei:
                while True:
                    s.next(timeout=DEADLINE_S + 10)
            e = ei.value
            assert e.stage and "step" in e.stage, (
                f"error does not name the stage: {e}")
            assert "died" in str(e)
        detect_s = time.monotonic() - t0
        assert detect_s < DEADLINE_S, (
            f"stream attribution took {detect_s:.1f}s "
            f"(> {DEADLINE_S}s deadline)")

        # Event plane saw the death, entity-linked to the dead graph.
        evs = _wait(
            lambda: [e for e in state.list_events(entity=dag_id)
                     if e["kind"] == "dag_stage_death"] or None,
            what="dag_stage_death event")
        assert "step" in evs[0]["attrs"]["stage"]

        # The engine rebuilt and RESUMED: a fresh request completes with
        # the same greedy tokens the pre-chaos model produced (new stage
        # actors, same seed, same shards).
        s2 = pipe.submit([1, 2], SamplingParams(temperature=0.0,
                                                max_tokens=4))
        assert _drain_bounded(s2, budget_s=90.0) == warm[0]
        assert pipe.num_active == 0
    finally:
        pipe.shutdown()
    # Shutdown after chaos is clean: no open streams, no stage actors.
    assert pipe._actors == [] and pipe._dag is None


def test_shutdown_mid_generation_never_hangs(ray_start_4cpu):
    """shutdown() with streams open ends every stream promptly (engine
    shut down => streams end; a consumer blocked in next() is released)."""
    from ray_tpu.llm.pipeline import PipelinedEngine

    pipe = PipelinedEngine(LLMConfig(**CFG_KW), n_stages=2, max_batch=4,
                           microbatch=2)
    s = pipe.submit([3, 4], SamplingParams(temperature=0.0,
                                           max_tokens=200))
    s.next(timeout=30)  # generation is live
    t0 = time.monotonic()
    pipe.shutdown()
    # The stream ends (StopIteration) rather than waiting out 200 tokens.
    _drain_bounded(s, budget_s=30.0)
    assert time.monotonic() - t0 < 30.0
    with pytest.raises(RuntimeError, match="shut down"):
        pipe.submit([1], SamplingParams(max_tokens=2))
