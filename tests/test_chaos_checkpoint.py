"""Checkpoint atomicity under chaos (README "Checkpointing & storage"):
transient sim:// write failures are retried with backoff; a severed
backend or a worker killed mid-save_async never corrupts the last
committed checkpoint — the partial upload stays invisible (no manifest)
and is GC'd; a corrupt controller snapshot is quarantined, not
crash-looped.
"""

import json
import os
import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import storage
from ray_tpu._private.rtconfig import CONFIG
from ray_tpu.storage import StorageTransientError
from ray_tpu.storage.sim import faults
from ray_tpu.train import checkpoint as ck


@pytest.fixture(autouse=True)
def _clean_sim():
    faults().clear()
    yield
    faults().clear()


def _state(tag: float):
    return {"params": {"w": np.full((64, 8), tag),
                       "b": np.arange(8.0) + tag},
            "step": int(tag)}


def test_transient_write_failures_retried_with_backoff(tmp_path, monkeypatch):
    monkeypatch.setitem(CONFIG._overrides, "ckpt_retry_base_s", 0.05)
    root = "sim://" + str(tmp_path / "cks")
    d = storage.join(root, "checkpoint_000001")
    # 2nd put fails twice (the put itself, then its first retry), then heals.
    faults().add_rule(op="put", after=1, times=2)
    t0 = time.perf_counter()
    h = ck.save_async(_state(1), d, step=1)
    h.result(60)
    elapsed = time.perf_counter() - t0
    assert h.stats["retries"] == 2
    assert faults().stats.get("put") == 2
    assert elapsed >= 0.05 + 0.1  # exponential backoff actually slept
    assert np.array_equal(ck.restore(d)["params"]["w"], np.full((64, 8), 1.0))


def test_sever_mid_save_previous_commit_intact(tmp_path, monkeypatch):
    """The backend 'partitions' partway through a save: the save fails
    after its retry budget, the previous committed checkpoint still loads
    bitwise, and the partial is GC'd."""
    monkeypatch.setitem(CONFIG._overrides, "ckpt_retries", 1)
    monkeypatch.setitem(CONFIG._overrides, "ckpt_retry_base_s", 0.01)
    root = "sim://" + str(tmp_path / "cks")
    d1 = storage.join(root, "checkpoint_000001")
    d2 = storage.join(root, "checkpoint_000002")
    ck.save_async(_state(1), d1, step=1).result(60)

    # Everything on checkpoint 2 fails after its first 2 ops (the
    # in-progress marker + one shard land, then the link goes down).
    faults().add_rule(op="*", after=2, match=lambda p: "checkpoint_000002" in p)
    h = ck.save_async(_state(2), d2, step=2)
    with pytest.raises(StorageTransientError):
        h.result(60)
    faults().clear()

    assert ck.load_manifest(d2) is None, "partial must never look committed"
    assert ck.latest_checkpoint(root) == d1
    st = ck.restore(d1)
    assert np.array_equal(st["params"]["w"], np.full((64, 8), 1.0))
    assert st["step"] == 1
    # the partial shows up as such, then GC collects it
    rows = ck.list_checkpoints(root)
    assert [(r["name"], r["committed"]) for r in rows] == [
        ("checkpoint_000001", True), ("checkpoint_000002", False)]
    assert ck.gc_partials(root, grace_s=0) == [d2]
    assert ck.restore(d1)["step"] == 1  # survivor untouched by GC


@ray_tpu.remote
class _Saver:
    def save(self, d, tag, latency=0.0):
        # Workers run on the head's propagated config snapshot, so the
        # latency knob must go through _system_config, not env.
        from ray_tpu._private.rtconfig import CONFIG

        CONFIG.apply_system_config({"sim_storage_latency_s": latency})
        state = {"params": {"w": np.full((64, 8), float(tag)),
                            "b": np.arange(8.0) + float(tag)},
                 "step": int(tag)}
        ck.save(state, d, step=int(tag))
        return True


def test_kill_worker_mid_save_last_committed_loads(ray_start_2cpu, tmp_path):
    """A train-style worker dies mid-save_async (SIGKILL, no cleanup):
    the previous committed checkpoint loads intact and the orphaned
    partial is GC'd."""
    fs_root = str(tmp_path / "cks")
    root = "sim://" + fs_root
    d1 = storage.join(root, "checkpoint_000001")
    d2 = storage.join(root, "checkpoint_000002")

    saver = _Saver.remote()
    assert ray_tpu.get(saver.save.remote(d1, 1), timeout=60)

    # Slow every storage op in the saver's process, then kill it as soon
    # as the save's first object (the in-progress marker) hits storage.
    ref = saver.save.remote(d2, 2, latency=0.3)
    marker = os.path.join(fs_root, "checkpoint_000002", "_inprogress_r0")
    deadline = time.monotonic() + 30
    while not os.path.exists(marker):
        assert time.monotonic() < deadline, "save never started"
        time.sleep(0.01)
    ray_tpu.kill(saver)
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=30)

    assert ck.load_manifest(d2) is None, "killed save must not be committed"
    st = ck.restore(d1)
    assert np.array_equal(st["params"]["w"], np.full((64, 8), 1.0))
    assert ck.latest_checkpoint(root) == d1
    assert ck.gc_partials(root, grace_s=0) == [d2]
    rows = ck.list_checkpoints(root)
    assert [r["name"] for r in rows] == ["checkpoint_000001"]


def test_corrupt_controller_snapshot_quarantined(tmp_path):
    """A truncated/corrupt persisted controller snapshot must not
    crash-loop startup: the head comes up fresh and the bad file is moved
    aside with a .corrupt suffix."""
    from tests.test_controller_ft import _free_port, _spawn_head

    port = _free_port()
    session_dir = str(tmp_path / "session")
    persist_dir = str(tmp_path / "persist")
    os.makedirs(session_dir, exist_ok=True)
    os.makedirs(persist_dir, exist_ok=True)
    bad = os.path.join(persist_dir, "controller_state.pkl")
    with open(bad, "wb") as f:
        f.write(b"\x80\x05 this is not a pickle")
    head, info = _spawn_head(port, session_dir, persist_dir)
    try:
        assert os.path.exists(bad + ".corrupt"), "bad snapshot not quarantined"
        assert not os.path.exists(bad)
        # and the controller is actually serving
        from ray_tpu._private import rpc

        assert json.loads(json.dumps(info))["address"]
    finally:
        head.terminate()
        head.wait(timeout=30)
