"""ray-tpu CLI: start --head / start --address / status / stop.

reference tests: python/ray/tests/test_cli.py (ray start/stop paths).
"""

import json
import os
import subprocess
import sys

import pytest

import ray_tpu

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cli(session_dir, *args, timeout=120):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.scripts.cli",
         "--session-dir", str(session_dir), *args],
        capture_output=True, text=True, timeout=timeout, env=env)


def test_cli_cluster_lifecycle(tmp_path, shutdown_only):
    sdir = tmp_path / "session"
    try:
        r = _cli(sdir, "start", "--head", "--num-cpus", "1", "--port", "0")
        assert r.returncode == 0, r.stderr
        info = json.load(open(sdir / "head.json"))

        r = _cli(sdir, "start", "--address", info["address"], "--num-cpus", "2")
        assert r.returncode == 0, r.stderr

        r = _cli(sdir, "status")
        assert r.returncode == 0, r.stderr
        assert r.stdout.count("ALIVE") == 2, r.stdout

        # A driver connects and runs work on BOTH CLI-started nodes.
        ray_tpu.init(address=info["address"])

        @ray_tpu.remote(scheduling_strategy="SPREAD")
        def where():
            return os.environ.get("RT_NODE_ID")

        nodes = set(ray_tpu.get([where.remote() for _ in range(6)], timeout=120))
        assert len(nodes) == 2
        ray_tpu.shutdown()
    finally:
        r = _cli(sdir, "stop")
    assert "stopped" in r.stdout
    assert not (sdir / "head.json").exists()


def test_cli_job_workflow(tmp_path):
    """ray-tpu job submit/status/logs/list against a CLI-started head
    (reference dashboard/modules/job/tests + `ray job submit`)."""
    sdir = tmp_path / "session"
    try:
        r = _cli(sdir, "start", "--head", "--num-cpus", "1", "--port", "0")
        assert r.returncode == 0, r.stderr

        r = _cli(sdir, "job", "submit", "--submission-id", "jobA", "--",
                 "python", "-c", "print(6 * 7)")
        assert r.returncode == 0, r.stderr + r.stdout
        assert "42" in r.stdout and "SUCCEEDED" in r.stdout, r.stdout

        r = _cli(sdir, "job", "status", "jobA")
        assert r.stdout.strip() == "SUCCEEDED", r.stdout

        r = _cli(sdir, "job", "logs", "jobA")
        assert "42" in r.stdout

        r = _cli(sdir, "job", "list")
        assert "jobA" in r.stdout and "SUCCEEDED" in r.stdout
    finally:
        _cli(sdir, "stop")
