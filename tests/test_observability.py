"""Observability: task events -> chrome-trace timeline, state list APIs,
worker log streaming to the driver.

reference tests: python/ray/tests/test_state_api.py, test_timeline.py,
test_output.py (log_to_driver).
"""

import json
import time

import pytest

import ray_tpu
from ray_tpu.util import state


def test_timeline_chrome_trace(ray_start_2cpu, tmp_path):
    @ray_tpu.remote
    def work(i):
        time.sleep(0.05)
        return i

    ray_tpu.get([work.remote(i) for i in range(6)], timeout=120)
    time.sleep(0.3)  # let the event batches drain to the controller

    out = str(tmp_path / "trace.json")
    trace = ray_tpu.timeline(filename=out)
    xs = [e for e in trace if e.get("ph") == "X"]
    assert len(xs) >= 6
    ev = next(e for e in xs if e["name"] == "work")
    assert ev["dur"] >= 0.04 * 1e6  # the sleep is visible
    assert ev["args"]["ok"] is True
    metas = [e for e in trace if e.get("ph") == "M"]
    assert any(m["name"] == "process_name" for m in metas)
    assert any(m["name"] == "thread_name" for m in metas)
    # the file is valid chrome-trace JSON
    loaded = json.load(open(out))
    assert loaded == trace


def test_state_list_apis(ray_start_2cpu):
    @ray_tpu.remote
    def fin():
        return 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return "pong"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    ray_tpu.get([fin.remote() for _ in range(3)], timeout=60)
    big = ray_tpu.put(b"x" * (1 << 20))  # non-inline: stays in directory
    time.sleep(0.3)

    tasks = state.list_tasks()
    assert any(t["name"] == "fin" and t["state"] == "FINISHED" for t in tasks)
    assert any(t["name"] == "ping" for t in tasks)

    actors = state.list_actors()
    assert any(x["class"] == "A" and x["state"] == "ALIVE" for x in actors)

    objs = state.list_objects()
    assert any(o["object_id"] == big.hex() for o in objs)

    nodes = state.list_nodes()
    assert len(nodes) == 1 and nodes[0]["alive"]

    summary = state.summarize_tasks()
    assert summary.get("fin:FINISHED", 0) >= 3


def test_worker_logs_stream_to_driver(ray_start_2cpu, capfd):
    @ray_tpu.remote
    def shout():
        print("HELLO-FROM-WORKER-xyzzy")
        return 1

    assert ray_tpu.get(shout.remote(), timeout=60) == 1
    deadline = time.monotonic() + 10
    seen = False
    while time.monotonic() < deadline and not seen:
        time.sleep(0.2)
        err = capfd.readouterr().err
        seen = "HELLO-FROM-WORKER-xyzzy" in err
    assert seen, "worker stdout never reached the driver"


def test_live_worker_stack_dump(ray_start_2cpu):
    """Live thread stacks of a running worker via SIGUSR1 + faulthandler
    (the py-spy/reporter-agent role): the dump must show the worker's
    executing frame."""
    import time as _t

    @ray_tpu.remote
    class Busy:
        def spin(self, seconds):
            deadline = _t.time() + seconds
            while _t.time() < deadline:
                _t.sleep(0.01)
            return "done"

    a = Busy.remote()
    ref = a.spin.remote(8.0)
    _t.sleep(1.0)  # ensure the call is executing
    w = ray_tpu._private.worker.global_worker()
    # resolve the actor's worker id via the controller
    info = w.io.run(w.controller.call(
        "get_actor_info", actor_id=a._actor_id, wait=True))
    rep = w.io.run(w.controller.call(
        "worker_stacks", worker_id=info["worker_id"], node_id=None),
        timeout=15)
    assert rep["found"], rep
    assert "spin" in rep["stacks"], rep["stacks"][:500]
    assert ray_tpu.get(ref, timeout=60) == "done"
